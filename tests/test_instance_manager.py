"""Declarative autoscaler instance manager (reference: the v2
InstanceManager/Reconciler tests under
python/ray/autoscaler/v2/tests/ — lifecycle FSM, idempotent launches,
convergence after provider failures)."""

import os

import pytest

from ray_tpu.autoscaler.instance_manager import (
    FAILED, JOINED, PROVISIONING, REQUESTED, RUNNING, TERMINATED,
    TERMINATING, CloudInstance, CloudProvider, FakeCloudProvider, Instance,
    InstanceManager, InstanceStore)


def counts(mgr):
    out = {}
    for i in mgr.store.all():
        out[i.status] = out.get(i.status, 0) + 1
    return out


class TestLifecycle:
    def test_launch_provisions_and_runs(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 3})
        assert counts(mgr) == {REQUESTED: 3}
        assert len(prov.request_log) == 1  # ONE slice request for 3 hosts
        mgr.reconcile({"worker": 3})
        assert counts(mgr) == {RUNNING: 3}
        # Converged: no further provider requests.
        mgr.reconcile({"worker": 3})
        assert len(prov.request_log) == 1

    def test_join_binding(self):
        prov = FakeCloudProvider()
        joined = {}
        mgr = InstanceManager(prov, joined_pids=lambda: joined)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        insts = mgr.store.alive()
        prov.mark_joined_pid(insts[0].cloud_id, 4242)
        mgr.reconcile({"worker": 2})  # picks up os_pid
        joined[4242] = "node-abc"
        mgr.reconcile({"worker": 2})
        st = {i.cloud_id: i.status for i in mgr.store.all()}
        assert st[insts[0].cloud_id] == JOINED
        ray_ids = [i.ray_node_id for i in mgr.store.all()
                   if i.status == JOINED]
        assert ray_ids == ["node-abc"]

    def test_scale_down_prefers_unjoined(self):
        prov = FakeCloudProvider()
        joined = {}
        mgr = InstanceManager(prov, joined_pids=lambda: joined)
        mgr.reconcile({"worker": 3})
        mgr.reconcile({"worker": 3})
        insts = mgr.store.alive()
        prov.mark_joined_pid(insts[0].cloud_id, 7)
        mgr.reconcile({"worker": 3})
        joined[7] = "node-j"
        mgr.reconcile({"worker": 3})
        mgr.reconcile({"worker": 1})
        alive = mgr.store.alive()
        assert len(alive) == 1 and alive[0].status == JOINED

    def test_desired_zero_drains_type(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        mgr.reconcile({})
        mgr.reconcile({})
        assert all(i.status in (TERMINATING, TERMINATED)
                   for i in mgr.store.all())


class TestFailureConvergence:
    def test_gang_killed_mid_launch_converges(self):
        """The judge scenario: a multi-host slice dies while queued; the
        reconciler must buy a replacement slice and converge."""
        prov = FakeCloudProvider(provision_delay_s=3600.0)  # stuck queued
        mgr = InstanceManager(prov)
        mgr.reconcile({"slice_host": 4})
        rid = prov.request_log[0][0]
        assert counts(mgr) == {PROVISIONING: 4} or \
            counts(mgr) == {REQUESTED: 4}
        prov.kill_request(rid)                  # capacity reclaimed
        prov.provision_delay_s = 0.0            # next request succeeds
        mgr.reconcile({"slice_host": 4})        # observes FAILED, re-buys
        assert counts(mgr).get(FAILED) == 4
        mgr.reconcile({"slice_host": 4})
        c = counts(mgr)
        assert c.get(RUNNING) == 4 and c.get(FAILED) == 4
        assert len(prov.request_log) == 2       # exactly one replacement

    def test_single_host_failure_replaced(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 3})
        mgr.reconcile({"worker": 3})
        victim = mgr.store.alive()[1]
        prov.kill_instance(victim.cloud_id)
        mgr.reconcile({"worker": 3})
        mgr.reconcile({"worker": 3})
        c = counts(mgr)
        assert c.get(RUNNING) == 3 and c.get(FAILED) == 1

    def test_cloud_loses_running_instance(self):
        """Preemption: cloud forgets a RUNNING instance entirely."""
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        victim = mgr.store.alive()[0]
        with prov._lock:
            del prov._instances[victim.cloud_id]
            del prov._created_at[victim.cloud_id]
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        c = counts(mgr)
        assert c.get(RUNNING) == 2 and c.get(TERMINATED) == 1

    def test_scale_down_before_hosts_appear_no_orphans(self):
        """Desired drops while the slice request is still queued: the
        drained entries stay TERMINATING, bind the late-materializing
        hosts, and terminate them — no orphaned cloud instances."""
        prov = FakeCloudProvider(provision_delay_s=0.15)  # hosts lag
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 3})
        mgr.reconcile({"worker": 1})  # scale down pre-materialization
        import time as _t
        _t.sleep(0.2)
        for _ in range(4):
            mgr.reconcile({"worker": 1})
        cloud = {c.cloud_id: c.status for c in prov.describe()}
        assert sum(1 for s in cloud.values() if s == "running") == 1, cloud
        assert sum(1 for s in cloud.values() if s == "terminated") == 2

    def test_terminate_failure_retried(self):
        class FlakyTerm(FakeCloudProvider):
            fails = 1

            def terminate(self, cloud_ids):
                if FlakyTerm.fails:
                    FlakyTerm.fails = 0
                    raise ConnectionError("api down")
                super().terminate(cloud_ids)

        prov = FlakyTerm()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 0})   # terminate raises, swallowed
        for _ in range(3):
            mgr.reconcile({"worker": 0})
        cloud = {c.cloud_id: c.status for c in prov.describe()}
        assert all(s == "terminated" for s in cloud.values()), cloud

    def test_provider_request_exception_retried(self):
        class Flaky(FakeCloudProvider):
            def __init__(self):
                super().__init__()
                self.fail_next = 1

            def request(self, request_id, node_type, count):
                if self.fail_next:
                    self.fail_next -= 1
                    raise ConnectionError("cloud API down")
                super().request(request_id, node_type, count)

        prov = Flaky()
        mgr = InstanceManager(prov)
        mgr.reconcile({"worker": 2})            # request raises
        assert counts(mgr) == {REQUESTED: 2}
        mgr.retry_pending_requests()            # idempotent re-issue
        mgr.reconcile({"worker": 2})
        assert counts(mgr) == {RUNNING: 2}
        assert len(prov.request_log) == 1       # same request id, once


class TestPersistence:
    def test_journal_survives_restart(self, tmp_path):
        path = str(tmp_path / "instances.jsonl")
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, store=InstanceStore(path))
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        # "Crash": new manager over the same journal + same provider.
        mgr2 = InstanceManager(prov, store=InstanceStore(path))
        assert counts(mgr2) == {RUNNING: 2}
        mgr2.reconcile({"worker": 2})
        # Idempotent: the restarted manager does NOT re-buy.
        assert len(prov.request_log) == 1

    def test_requested_entries_reissue_idempotently(self, tmp_path):
        """Crash after persisting REQUESTED but before the provider call:
        the restarted manager re-issues the SAME request id."""
        path = str(tmp_path / "instances.jsonl")

        class Dropping(FakeCloudProvider):
            drops = 1

            def request(self, request_id, node_type, count):
                if Dropping.drops:
                    Dropping.drops = 0
                    return  # "crash" before the API call landed
                super().request(request_id, node_type, count)

        prov = Dropping()
        mgr = InstanceManager(prov, store=InstanceStore(path))
        mgr.reconcile({"worker": 3})
        assert not prov.request_log
        mgr2 = InstanceManager(prov, store=InstanceStore(path))
        mgr2.retry_pending_requests()
        mgr2.reconcile({"worker": 3})
        assert counts(mgr2) == {RUNNING: 3}
        assert len(prov.request_log) == 1

    def test_torn_tail_ignored(self, tmp_path):
        path = str(tmp_path / "instances.jsonl")
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, store=InstanceStore(path))
        mgr.reconcile({"worker": 1})
        with open(path, "a") as f:
            f.write('{"instance_id": "zz", "node_t')  # torn write
        mgr2 = InstanceManager(prov, store=InstanceStore(path))
        assert len(mgr2.store.all()) == 1


class TestPrebuyOnNotice:
    """Pre-buy at preemption-NOTICE time: the replacement is REQUESTED
    while the victim still runs, so the drain deadline is spent
    provisioning instead of wasted (the closed elasticity loop)."""

    def _converge(self, mgr, desired, want_status=RUNNING, want=None):
        for _ in range(50):
            mgr.reconcile(desired)
            live = [i for i in mgr.store.alive()
                    if i.status == want_status]
            if len(live) == (want if want is not None
                             else sum(desired.values())):
                return live
        raise AssertionError(
            f"never converged to {desired} at {want_status}: "
            f"{counts(mgr)}")

    def test_notice_prebuys_replacement_before_death(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, drain_hook=lambda *a: None)
        self._converge(mgr, {"worker": 2})
        victim = mgr.store.alive()[0]
        n_requests = len(prov.request_log)
        prov.preempt_notice(victim.cloud_id, deadline_s=30.0)
        mgr.reconcile({"worker": 2})
        # Replacement requested IMMEDIATELY — victim still running.
        assert len(prov.request_log) == n_requests + 1
        statuses = {i.cloud_id: i.status for i in mgr.store.all()}
        assert statuses[victim.cloud_id] == RUNNING
        # Steady while the notice stands: no second replacement.
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        assert len(prov.request_log) == n_requests + 1
        # The victim dies; the fleet is already whole — no NEW request.
        prov.lose_instance(victim.cloud_id)
        self._converge(mgr, {"worker": 2})
        assert len(prov.request_log) == n_requests + 1

    def test_prebuy_disabled_buys_only_after_death(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, drain_hook=lambda *a: None,
                              prebuy=False)
        self._converge(mgr, {"worker": 2})
        victim = mgr.store.alive()[0]
        n_requests = len(prov.request_log)
        prov.preempt_notice(victim.cloud_id, deadline_s=30.0)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        assert len(prov.request_log) == n_requests  # naive: waits
        prov.lose_instance(victim.cloud_id)
        mgr.reconcile({"worker": 2})
        mgr.reconcile({"worker": 2})
        assert len(prov.request_log) == n_requests + 1  # after death

    def test_notice_storm_bounded_by_max_pending_prebuys(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, drain_hook=lambda *a: None,
                              max_pending_prebuys=2)
        self._converge(mgr, {"worker": 5})
        victims = mgr.store.alive()[:4]
        for v in victims:
            prov.preempt_notice(v.cloud_id, deadline_s=30.0)
        mgr.reconcile({"worker": 5})
        # At most 2 victims discounted at once -> at most 2 replacement
        # hosts requested in the first wave.
        extra = sum(n for _rid, _nt, n in prov.request_log) - 5
        assert extra == 2
        # As the storm's victims die, later waves replace the rest.
        for v in victims:
            prov.lose_instance(v.cloud_id)
        self._converge(mgr, {"worker": 5})

    def test_cancelled_notice_self_corrects_surplus(self):
        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, drain_hook=lambda *a: None)
        self._converge(mgr, {"worker": 2})
        victim = mgr.store.alive()[0]
        prov.preempt_notice(victim.cloud_id, deadline_s=30.0)
        mgr.reconcile({"worker": 2})  # pre-buys
        # The cloud cancels the reclaim: notice disappears, the victim
        # lives — the reconciler terminates the surplus replacement and
        # converges back to 2.
        with prov._lock:
            prov._notices.clear()
        for _ in range(50):
            mgr.reconcile({"worker": 2})
            running = [i for i in mgr.store.alive()
                       if i.status == RUNNING]
            if len(running) == 2:
                break
        assert len([i for i in mgr.store.alive()
                    if i.status == RUNNING]) == 2
        # The survivor is the original victim (doomed-first surplus
        # ordering must not have killed it while it was noticed).
        assert any(i.cloud_id == victim.cloud_id
                   for i in mgr.store.alive())


class TestLoseInstanceChaos:
    def test_chaos_runner_lose_instance_hits_provider(self):
        """The chaos harness's provider-level loss (no runtime signal)
        lands on FakeCloudProvider.lose_instance: the host vanishes from
        describe() entirely — the un-noticed spot reclaim."""
        import time

        from ray_tpu.devtools.chaos import ChaosRunner, ChaosSchedule

        prov = FakeCloudProvider()
        mgr = InstanceManager(prov, drain_hook=lambda *a: None)
        for _ in range(10):
            mgr.reconcile({"worker": 2})
        cid = mgr.store.alive()[0].cloud_id
        sched = ChaosSchedule().lose_instance(0.0, cid)
        runner = ChaosRunner(None, sched, provider=prov)
        runner.start()
        assert runner.join(timeout=30)
        runner.stop()
        assert runner.log and runner.log[0]["ok"]
        assert runner.log[0]["cloud_id"] == cid
        assert cid not in {ci.cloud_id for ci in prov.describe()}
        # The manager counts it preempted and replaces it.
        for _ in range(50):
            mgr.reconcile({"worker": 2})
            if len([i for i in mgr.store.alive()
                    if i.status == RUNNING]) == 2:
                break
        assert len([i for i in mgr.store.alive()
                    if i.status == RUNNING]) == 2

    def test_schedule_mixes_noticed_and_unnoticed(self):
        """spot_fleet schedules carry both preempts (notice + kill) and
        bare kills (no notice), seed-deterministic."""
        from ray_tpu.devtools.chaos import ChaosSchedule

        a = ChaosSchedule.spot_fleet(seed=3, rate=0.5, horizon_s=60.0,
                                     no_notice_frac=0.3)
        b = ChaosSchedule.spot_fleet(seed=3, rate=0.5, horizon_s=60.0,
                                     no_notice_frac=0.3)
        assert [(e.at_s, e.action, e.deadline_s) for e in a.events] == \
            [(e.at_s, e.action, e.deadline_s) for e in b.events]
        kinds = {e.action for e in a.events}
        assert "preempt" in kinds and "kill" in kinds
        assert all(e.at_s < 60.0 for e in a.events)
