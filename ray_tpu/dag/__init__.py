"""Compiled graphs: lazy DAGs of actor-method calls executed over channels.

Reference: python/ray/dag/ — DAGNode (dag_node.py), InputNode/
InputAttributeNode (input_node.py), ClassMethodNode, MultiOutputNode
(output_node.py), ``experimental_compile`` (dag/compiled_dag_node.py:804
CompiledDAG).  Interpreted ``execute`` submits ordinary actor tasks;
compiled execution replaces per-call RPC with persistent per-actor loops
exchanging messages over shared-memory channels (ray_tpu/dag/channel.py) —
the ADAG model: plan once, push data through a static pipeline.

Example::

    with InputNode() as inp:
        x = a.step.bind(inp)
        y = b.step.bind(x)
    dag = y.experimental_compile()
    ref = dag.execute(batch)
    out = ref.get()
    dag.teardown()
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .channel import ShmChannel
from .compiled_dag import CompiledDAG

__all__ = ["DAGNode", "InputNode", "InputAttributeNode", "ClassMethodNode",
           "MultiOutputNode", "CompiledDAG", "ShmChannel",
           "CollectiveOutputNode", "allreduce_bind"]


class DAGNode:
    """Base class for graph nodes.  Nodes are immutable once bound."""

    def _upstream(self) -> List["DAGNode"]:
        """Direct DAGNode dependencies of this node."""
        return []

    # -- interpreted execution --------------------------------------------

    def execute(self, *args, **kwargs):
        """Execute the DAG by submitting ordinary actor tasks; returns the
        ObjectRef(s) of this node's result (reference: dag_node.py
        execute)."""
        memo: Dict[int, Any] = {}
        return self._eval(memo, args, kwargs)

    def _eval(self, memo: Dict[int, Any], args, kwargs):
        key = id(self)
        if key not in memo:
            memo[key] = self._eval_impl(memo, args, kwargs)
        return memo[key]

    def _eval_impl(self, memo, args, kwargs):
        raise NotImplementedError

    # -- compiled execution ------------------------------------------------

    def experimental_compile(self, *, buffer_size_bytes: int = 1 << 20,
                             submit_timeout: float = 30.0) -> CompiledDAG:
        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes,
                           submit_timeout=submit_timeout)


class InputNode(DAGNode):
    """The DAG's input placeholder; a context manager for bind-time use
    (reference: dag/input_node.py)."""

    def __init__(self):
        self._attr_cache: Dict[Any, "InputAttributeNode"] = {}

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __getitem__(self, key: int) -> "InputAttributeNode":
        if key not in self._attr_cache:
            self._attr_cache[key] = InputAttributeNode(self, key)
        return self._attr_cache[key]

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        if key not in self._attr_cache:
            self._attr_cache[key] = InputAttributeNode(self, key)
        return self._attr_cache[key]

    def _eval_impl(self, memo, args, kwargs):
        if kwargs and not args:
            return kwargs
        if len(args) == 1 and not kwargs:
            return args[0]
        return args

    @staticmethod
    def extract(key: Any, args, kwargs):
        """Value an InputAttributeNode yields for execute(*args, **kwargs)."""
        if isinstance(key, int):
            return args[key]
        return kwargs[key]


class InputAttributeNode(DAGNode):
    """``inp[i]`` / ``inp.key`` — a positional/keyword slice of the input."""

    def __init__(self, parent: InputNode, key: Any):
        self._parent = parent
        self._key = key

    def _upstream(self) -> List[DAGNode]:
        return [self._parent]

    def _eval_impl(self, memo, args, kwargs):
        return InputNode.extract(self._key, args, kwargs)


class ClassMethodNode(DAGNode):
    """A bound actor-method call (reference: dag/class_node.py)."""

    def __init__(self, actor_handle, method_name: str,
                 bound_args: Tuple, bound_kwargs: Dict[str, Any]):
        self._actor = actor_handle
        self._method = method_name
        self._args = bound_args
        self._kwargs = bound_kwargs

    def _upstream(self) -> List[DAGNode]:
        return ([a for a in self._args if isinstance(a, DAGNode)]
                + [v for v in self._kwargs.values() if isinstance(v, DAGNode)])

    def _eval_impl(self, memo, args, kwargs):
        import ray_tpu
        r_args = []
        for a in self._args:
            v = a._eval(memo, args, kwargs) if isinstance(a, DAGNode) else a
            r_args.append(v)
        r_kwargs = {}
        for k, a in self._kwargs.items():
            v = a._eval(memo, args, kwargs) if isinstance(a, DAGNode) else a
            r_kwargs[k] = v
        method = getattr(self._actor, self._method)
        return method.remote(*r_args, **r_kwargs)

    def __repr__(self):
        return (f"ClassMethodNode({self._actor._class_name}."
                f"{self._method})")


class MultiOutputNode(DAGNode):
    """Marks several nodes as the DAG outputs; execute returns a list
    (reference: dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        self._outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self._outputs)

    def _eval_impl(self, memo, args, kwargs):
        return [o._eval(memo, args, kwargs) for o in self._outputs]


# Collective nodes import DAGNode from this module, so this import must sit
# below the class definitions (reference: dag/collective_node.py).
from .collective import CollectiveOutputNode, allreduce_bind  # noqa: E402
