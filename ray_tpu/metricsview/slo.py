"""Declarative SLO objectives + dual-window burn-rate alerting.

Reference: the Google SRE workbook's multiwindow, multi-burn-rate
alerts (and Prometheus alerting rules' ``for:`` clause).  An
``SloObjective`` targets any catalog series through the windowed query
engine — ``serve_request_latency p99 < 0.25``, ``train_goodput_ratio
avg > 0.5`` — and is evaluated against TWO windows:

* the **fast** window reacts (a real spike breaches it within seconds),
* the **slow** window confirms (a one-scrape blip cannot sustain a
  slow-window burn), so firing requires *both* to burn.

Burn rate: for quantile objectives the window's bad-observation
fraction (from histogram bucket deltas — the fraction of requests over
the threshold) divided by the error budget ``1 - q``; burn >= 1 means
the budget is being spent at least as fast as it accrues.  Scalar
objectives degenerate to breach/no-breach (burn 1 or 0).

State machine per objective::

    ok -> pending    fast window burns (stamped; nothing fires yet)
    pending -> firing  slow window confirms (after >= pending_for_s)
    pending -> ok      fast window recovers first (blip)
    firing -> resolved fast window recovers
    resolved -> ok     after cooldown_s (re-burn inside the cooldown
                       returns straight to firing: one flapping alert,
                       not a train of them)

Every transition lands in the export-event stream (EXPORT_ALERT), the
``ray_tpu_alerts_transitions_total{state}`` counter, and the bounded
transition ring that ``ray-tpu alerts`` / ``alerts.json`` render; the
``ray_tpu_alerts_firing`` gauge tracks how many objectives are firing
right now.

The engine is pull-evaluated from the ingest path (same cadence as the
store, no private timer thread) and from every alerts/query API call.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .query import parse_quantile, validate_agg

FIRING_GAUGE = "ray_tpu_alerts_firing"
TRANSITIONS_TOTAL = "ray_tpu_alerts_transitions_total"

_STATES = ("ok", "pending", "firing", "resolved")


@dataclass
class SloObjective:
    """One service-level objective on a catalog series."""

    name: str                 # unique objective id, e.g. "serve-p99"
    metric: str               # series name (catalog or user metric)
    agg: str                  # "p99" | "avg" | "rate" | ...
    op: str                   # healthy direction: value OP threshold
    threshold: float
    tags: Optional[Dict[str, str]] = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    pending_for_s: float = 0.0   # min dwell in pending before firing
    cooldown_s: float = 60.0     # resolved -> ok hold-down
    description: str = ""

    def __post_init__(self):
        if self.op not in ("<", "<=", ">", ">="):
            raise ValueError(f"SloObjective {self.name!r}: op must be a "
                             f"comparison, got {self.op!r}")
        if not validate_agg(self.agg):
            raise ValueError(f"SloObjective {self.name!r}: unknown agg "
                             f"{self.agg!r}")
        if self.slow_window_s < self.fast_window_s:
            raise ValueError(f"SloObjective {self.name!r}: slow window "
                             f"must be >= fast window")

    def healthy(self, value: float) -> bool:
        if self.op == "<":
            return value < self.threshold
        if self.op == "<=":
            return value <= self.threshold
        if self.op == ">":
            return value > self.threshold
        return value >= self.threshold

    def spec(self) -> Dict[str, Any]:
        return {"name": self.name, "metric": self.metric, "agg": self.agg,
                "op": self.op, "threshold": self.threshold,
                "tags": dict(self.tags or {}),
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "pending_for_s": self.pending_for_s,
                "cooldown_s": self.cooldown_s,
                "description": self.description}

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "SloObjective":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in spec.items() if k in known})


@dataclass
class AlertState:
    """Live evaluation state for one objective."""

    objective: SloObjective
    state: str = "ok"
    since: Optional[float] = None        # entered current state (mono)
    pending_since: Optional[float] = None
    resolved_at: Optional[float] = None
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    value_fast: Optional[float] = None
    value_slow: Optional[float] = None
    no_data: bool = True
    transitions: int = 0

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {"objective": self.objective.name,
                "metric": self.objective.metric,
                "agg": self.objective.agg, "op": self.objective.op,
                "threshold": self.objective.threshold,
                "state": self.state,
                "since_s": round(now - self.since, 3)
                if self.since is not None else None,
                "burn_fast": round(self.burn_fast, 4),
                "burn_slow": round(self.burn_slow, 4),
                "value_fast": self.value_fast,
                "value_slow": self.value_slow,
                "no_data": self.no_data,
                "transitions": self.transitions}


class SloEngine:
    """Evaluates objectives against a ``SeriesStore``; owns no thread."""

    def __init__(self, store, event_sink: Optional[Callable] = None,
                 max_transitions: int = 256):
        self._store = store
        self._event_sink = event_sink  # (source_type, event_dict) -> None
        self._lock = threading.Lock()
        self._states: Dict[str, AlertState] = {}
        self._transitions: deque = deque(maxlen=max_transitions)

    # -- objective management ---------------------------------------------

    def set_objectives(self, objectives: List) -> int:
        """Replace the objective set (specs or SloObjective instances);
        evaluation state survives for objectives whose name persists."""
        objs = [o if isinstance(o, SloObjective)
                else SloObjective.from_spec(dict(o)) for o in objectives]
        with self._lock:
            old = self._states
            self._states = {}
            for o in objs:
                prev = old.get(o.name)
                if prev is not None:
                    prev.objective = o
                    self._states[o.name] = prev
                else:
                    self._states[o.name] = AlertState(o)
            self._refresh_gauge_locked()
        return len(objs)

    def add_objective(self, objective) -> None:
        o = objective if isinstance(objective, SloObjective) \
            else SloObjective.from_spec(dict(objective))
        with self._lock:
            if o.name in self._states:
                self._states[o.name].objective = o
            else:
                self._states[o.name] = AlertState(o)

    def objectives(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.objective.spec() for s in self._states.values()]

    # -- evaluation --------------------------------------------------------

    def _burn(self, obj: SloObjective, window_s: float, now: float):
        """(burn_rate, value, has_data) for one window."""
        res = self._store.query(obj.metric, window_s, obj.agg,
                                tags=obj.tags, now=now)
        value = res.get("value")
        if value is None:
            return 0.0, None, False
        q = parse_quantile(obj.agg)
        if q is not None and obj.op in ("<", "<="):
            budget = max(1e-9, 1.0 - q)
            bad = self._bad_fraction(obj, window_s, now)
            if bad is not None:
                return bad / budget, value, True
        return (0.0 if obj.healthy(value) else 1.0), value, True

    def _bad_fraction(self, obj: SloObjective, window_s: float,
                      now: float) -> Optional[float]:
        """Fraction of window observations over the threshold, from the
        cumulative-bucket delta (quantile objectives only)."""
        total = self._store.query(obj.metric, window_s, "delta",
                                  tags=obj.tags, now=now).get("value")
        if not total or total <= 0:
            return None
        # Observations at or under the threshold: cumulative count at
        # the threshold's bucket == a pNN-style CDF read.  Reuse the
        # bucket machinery by querying the share of points whose value
        # exceeds the threshold via per-bucket deltas.
        good = 0.0
        with self._store._lock:
            from .query import _window, hist_window_delta
            for s in self._store._matches(obj.metric, obj.tags):
                if s.mtype != "histogram" or not s.bounds:
                    continue
                base, win = _window(s.points, now - window_s, now)
                if not win:
                    continue
                dcounts, _ds, _dc = hist_window_delta(base, win)
                cum = 0.0
                for i, b in enumerate(s.bounds):
                    if b <= obj.threshold:
                        cum = dcounts[i] if i < len(dcounts) else cum
                    else:
                        break
                good += cum
        return max(0.0, min(1.0, (total - good) / total))

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the transitions it fired."""
        import time as _time
        now = _time.monotonic() if now is None else now
        fired: List[Dict[str, Any]] = []
        with self._lock:
            for st in self._states.values():
                obj = st.objective
                st.burn_fast, st.value_fast, has_fast = \
                    self._burn(obj, obj.fast_window_s, now)
                st.burn_slow, st.value_slow, has_slow = \
                    self._burn(obj, obj.slow_window_s, now)
                st.no_data = not (has_fast or has_slow)
                burning_fast = has_fast and st.burn_fast >= 1.0
                burning_slow = has_slow and st.burn_slow >= 1.0
                if st.state == "ok":
                    if burning_fast:
                        st.pending_since = now
                        fired.append(self._transition_locked(
                            st, "pending", now))
                elif st.state == "pending":
                    if not burning_fast:
                        st.pending_since = None
                        fired.append(self._transition_locked(st, "ok", now))
                    elif burning_slow and now - (st.pending_since or now) \
                            >= obj.pending_for_s:
                        fired.append(self._transition_locked(
                            st, "firing", now))
                elif st.state == "firing":
                    if not burning_fast:
                        st.resolved_at = now
                        fired.append(self._transition_locked(
                            st, "resolved", now))
                elif st.state == "resolved":
                    if burning_fast:
                        # Re-burn inside the cooldown: same incident.
                        fired.append(self._transition_locked(
                            st, "firing", now))
                    elif now - (st.resolved_at or now) >= obj.cooldown_s:
                        fired.append(self._transition_locked(st, "ok", now))
            self._refresh_gauge_locked()
        for t in fired:
            self._emit(t)
        return fired

    def _transition_locked(self, st: AlertState, to: str,
                           now: float) -> Dict[str, Any]:
        event = {"objective": st.objective.name,
                 "metric": st.objective.metric,
                 "agg": st.objective.agg,
                 "op": st.objective.op,
                 "threshold": st.objective.threshold,
                 "from": st.state, "to": to,
                 "value_fast": st.value_fast,
                 "value_slow": st.value_slow,
                 "burn_fast": round(st.burn_fast, 4),
                 "burn_slow": round(st.burn_slow, 4),
                 "age_s": 0.0, "_t": now}
        st.state = to
        st.since = now
        st.transitions += 1
        self._transitions.append(event)
        return event

    def _refresh_gauge_locked(self) -> None:
        from ray_tpu.util import telemetry
        telemetry.set_gauge(FIRING_GAUGE, sum(
            1 for s in self._states.values() if s.state == "firing"))

    def _emit(self, event: Dict[str, Any]) -> None:
        from ray_tpu.util import telemetry
        telemetry.inc(TRANSITIONS_TOTAL, tags={"state": event["to"]})
        if self._event_sink is not None:
            try:
                self._event_sink("EXPORT_ALERT",
                                 {k: v for k, v in event.items()
                                  if k != "_t"})
            except Exception as e:
                telemetry.note_swallowed("metricsview.alert_emit", e)

    # -- introspection -----------------------------------------------------

    def status(self, now: Optional[float] = None,
               recent: int = 50) -> Dict[str, Any]:
        import time as _time
        now = _time.monotonic() if now is None else now
        with self._lock:
            states = [s.snapshot(now) for s in self._states.values()]
            trans = [{**{k: v for k, v in t.items() if k != "_t"},
                      "age_s": round(now - t["_t"], 3)}
                     for t in list(self._transitions)[-recent:]]
        return {"objectives": states,
                "firing": sum(1 for s in states if s["state"] == "firing"),
                "transitions": trans}
