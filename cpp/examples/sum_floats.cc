// Example C++ consumer: sum a float32 tensor produced by Python workers.
//
//   ./sum_floats <segment> <offset> <nbytes> [buffer_index]
//
// Prints "count sum" of the float32 buffer — zero copies, no Python.

#include <cinttypes>
#include <cstdio>
#include <cstdlib>

#include "ray_tpu/object_reader.hpp"

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <segment> <offset> <nbytes> [buffer_index]\n",
                 argv[0]);
    return 2;
  }
  const std::string segment = argv[1];
  const uint64_t offset = std::strtoull(argv[2], nullptr, 10);
  const uint64_t nbytes = std::strtoull(argv[3], nullptr, 10);
  const size_t buf_idx = argc > 4 ? std::strtoul(argv[4], nullptr, 10) : 0;

  try {
    ray_tpu::ObjectView v = ray_tpu::open_object(segment, offset, nbytes);
    if (buf_idx >= v.buffers.size()) {
      std::fprintf(stderr, "object has %zu buffers, wanted %zu\n",
                   v.buffers.size(), buf_idx);
      return 1;
    }
    const auto &b = v.buffers[buf_idx];
    const auto *xs = reinterpret_cast<const float *>(b.data);
    const uint64_t n = b.size / sizeof(float);
    double sum = 0.0;
    for (uint64_t i = 0; i < n; ++i) {
      sum += xs[i];
    }
    std::printf("%" PRIu64 " %.6f\n", n, sum);
    return 0;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
