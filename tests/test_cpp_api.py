"""C++ user API tests: zero-copy arena reads from a compiled C++ program
(reference analog: cpp/ user API tests — here scoped to the data plane,
see cpp/README.md)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sum_floats_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("cppbin") / "sum_floats")
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-I", os.path.join(REPO, "cpp", "include"),
         os.path.join(REPO, "cpp", "examples", "sum_floats.cc"),
         "-o", out, "-lrt"],
        check=True, capture_output=True, timeout=300)
    return out


class TestCppObjectReader:
    def test_cpp_reads_python_tensor_zero_copy(self, sum_floats_bin,
                                               ray_start):
        rt = ray_start
        arr = np.arange(100_000, dtype=np.float32)
        ref = ray_tpu.put(arr)
        # The arena descriptor: ("shma", segment, offset, nbytes, id) for
        # the native store, ("shm", name, nbytes) for the fallback.
        desc = rt.node.store.descriptor(ref.id())
        assert desc is not None
        if desc[0] == "shma":
            _, seg, off, nbytes, _ = desc
        else:
            _, seg, nbytes = desc
            off = 0
        out = subprocess.run(
            [sum_floats_bin, seg, str(off), str(nbytes)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        count, total = out.stdout.split()
        assert int(count) == 100_000
        assert float(total) == pytest.approx(float(arr.sum()), rel=1e-6)

    def test_cpp_rejects_corrupt_range(self, sum_floats_bin, ray_start):
        rt = ray_start
        ref = ray_tpu.put(np.ones(50_000, np.float32))
        desc = rt.node.store.descriptor(ref.id())
        seg = desc[1]
        # Lie about the length: the reader must fail cleanly, not crash.
        out = subprocess.run(
            [sum_floats_bin, seg, "0", str(1 << 40)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode != 0
        assert "error" in out.stderr or "segment" in out.stderr


@pytest.fixture(scope="module")
def produce_tensor_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("cppbin") / "produce_tensor")
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-I", os.path.join(REPO, "cpp", "include"),
         os.path.join(REPO, "cpp", "examples", "produce_tensor.cc"),
         "-o", out, "-lrt"],
        check=True, capture_output=True, timeout=300)
    return out


class TestCppTensorWriter:
    def test_cpp_writes_python_reads_zero_copy(self, produce_tensor_bin):
        """The producer half of the native data plane: a C++ loader
        writes typed tensors, Python maps them zero-copy
        (cpp/include/ray_tpu/tensor_writer.hpp <-> util/cpp_io.py)."""
        from ray_tpu.util import cpp_io
        seg = f"/rt_test_cpp_{os.getpid()}"
        subprocess.run([produce_tensor_bin, seg, "8"], check=True,
                       capture_output=True, timeout=60)
        try:
            views, keep = cpp_io.import_tensors(seg)
            x, y = views
            assert x.shape == (8, 16) and x.dtype == np.float32
            np.testing.assert_allclose(
                x.ravel(), np.arange(128, dtype=np.float32) * 0.5)
            np.testing.assert_array_equal(
                y, (np.arange(8) ** 2).astype(np.int32))
            # Zero-copy: the view aliases the shm mapping.
            assert not x.flags["OWNDATA"]
            del views, x, y
            keep.close()
        finally:
            try:
                from multiprocessing import shared_memory
                shared_memory.SharedMemory(name=seg.lstrip("/")).unlink()
            except FileNotFoundError:
                pass

    def test_python_export_roundtrip(self):
        from ray_tpu.util import cpp_io
        seg = f"/rt_test_pio_{os.getpid()}"
        a = np.arange(24, dtype=np.float64).reshape(4, 6)
        b = np.array([True, False, True])
        cpp_io.export_tensors(seg, [a, b])
        try:
            views, keep = cpp_io.import_tensors(seg)
            np.testing.assert_array_equal(views[0], a)
            np.testing.assert_array_equal(views[1], b)
            del views
            keep.close()
        finally:
            from multiprocessing import shared_memory
            shared_memory.SharedMemory(name=seg.lstrip("/")).unlink()


@pytest.fixture(scope="module")
def gateway_demo_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("cppbin") / "gateway_demo")
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-I", os.path.join(REPO, "cpp", "include"),
         os.path.join(REPO, "cpp", "examples", "gateway_demo.cc"),
         "-o", out, "-lrt"],
        check=True, capture_output=True, timeout=300)
    return out


class TestCppGateway:
    def test_cpp_submits_tasks_calls_actors_reads_tensors(
            self, gateway_demo_bin, ray_start):
        """The C++ task/actor API end to end (reference analog:
        cpp/src/ray/api.cc): a compiled native client submits a
        registered task, drives a named actor, and maps a tensor result
        zero-copy — through ray_tpu/cpp_gateway.py's schema'd protocol."""
        from ray_tpu import cpp_gateway

        def add(a, b):
            return a + b

        def make_tensor(n):
            return np.arange(n, dtype=np.float32)

        cpp_gateway.register_function("add", add)
        cpp_gateway.register_function("make_tensor", make_tensor)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.v = 0

            def bump(self, k):
                self.v += k
                return self.v

        Counter.options(name="counter", namespace="cppns").remote()
        cpp_gateway.export_actor("counter", namespace="cppns",
                                 methods=["bump"])

        gw = cpp_gateway.start()
        try:
            proc = subprocess.run(
                [gateway_demo_bin, gw.address[0], str(gw.address[1]),
                 gw.token],
                capture_output=True, text=True, timeout=120)
            assert proc.returncode == 0, proc.stderr
            out = proc.stdout
            assert "add -> 42" in out
            assert "bump -> 5 then 12" in out
            assert "tensor sum -> 2016.0" in out  # sum(range(64))
            # Wrong token is rejected.
            bad = subprocess.run(
                [gateway_demo_bin, gw.address[0], str(gw.address[1]),
                 "nope"], capture_output=True, text=True, timeout=60)
            assert bad.returncode != 0
        finally:
            gw.stop()
