"""Rotary position embeddings."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10000.0):
    """Returns (cos, sin) tables of shape [max_seq_len, head_dim//2]."""
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin, positions=None):
    """Rotate pairs of channels. x: [..., seq, head_dim].

    ``positions`` ([..., seq] int) selects rows of the tables — required when
    the sequence dim is sharded (ring/Ulysses shards pass absolute positions).
    """
    if positions is not None:
        cos = cos[positions]
        sin = sin[positions]
    else:
        cos = cos[: x.shape[-2]]
        sin = sin[: x.shape[-2]]
    # Broadcast tables over leading batch/head dims.
    while cos.ndim < x.ndim:
        cos = cos[None]
        sin = sin[None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
