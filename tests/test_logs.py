"""Log monitor + export event tests (reference analogs:
python/ray/tests/test_output.py worker-log redirection,
_private/log_monitor.py tailing, export_*.proto event records)."""

import json
import os
import time

import pytest

import ray_tpu


@pytest.fixture
def logged_runtime():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


@ray_tpu.remote
def chatty(msg):
    print(f"hello-from-worker {msg}")
    import sys
    print(f"warn-{msg}", file=sys.stderr)
    return msg


class TestLogMonitor:
    def test_worker_output_lands_in_session_logs(self, logged_runtime,
                                                 capsys):
        rt = logged_runtime
        assert os.path.isdir(rt.session_logs_dir)
        assert ray_tpu.get(chatty.remote("abc")) == "abc"
        # The worker's prints were redirected to per-worker files...
        deadline = time.time() + 10
        found_out = found_err = False
        while time.time() < deadline and not (found_out and found_err):
            for fname, _size in rt.ctl_log_files():
                if fname.endswith(".out") and "hello-from-worker abc" in \
                        "\n".join(rt.ctl_log_tail(fname)):
                    found_out = True
                if fname.endswith(".err") and "warn-abc" in \
                        "\n".join(rt.ctl_log_tail(fname)):
                    found_err = True
            time.sleep(0.1)
        assert found_out and found_err
        # ...and the monitor republishes them to the driver streams with a
        # worker prefix (reference: "(pid=...)" echo).
        deadline = time.time() + 5
        while time.time() < deadline:
            cap = capsys.readouterr()
            if "hello-from-worker abc" in cap.out:
                assert "(worker-" in cap.out
                break
            time.sleep(0.2)
        else:
            pytest.fail("worker stdout was not republished to the driver")

    def test_session_latest_symlink(self, logged_runtime):
        rt = logged_runtime
        base = os.path.dirname(rt.session_dir)
        link = os.path.join(base, "session_latest")
        assert os.path.islink(link)
        assert os.path.realpath(link) == os.path.realpath(rt.session_dir)

    def test_export_events_written(self, logged_runtime):
        rt = logged_runtime

        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == 1
        ray_tpu.kill(a)
        path = os.path.join(rt.session_logs_dir, "events.jsonl")
        deadline = time.time() + 10
        states = set()
        while time.time() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    recs = [json.loads(line) for line in f if line.strip()]
                states = {(r["source_type"], r.get("state"))
                          for r in recs}
                if ("EXPORT_ACTOR", "ALIVE") in states and \
                        ("EXPORT_ACTOR", "DEAD") in states:
                    break
            time.sleep(0.1)
        assert ("EXPORT_ACTOR", "ALIVE") in states
        assert ("EXPORT_ACTOR", "DEAD") in states
        for r in recs:
            assert "timestamp" in r
