"""Paged decode attention over a block-table KV cache.

The serving engine (llm/engine.py) keeps K/V in fixed-size pages with a
per-slot block table mapping sequence positions to pages.  One decode
step attends each slot's single query token over its pages.

Cache layout (per layer): ONE combined array

    kv_pages : [total_pages, page_size, 2 * num_kv_heads, head_dim]

with K at even and V at odd combined-head indices (k_h0, v_h0, k_h1,
...).  This is the layout the TPU ragged-paged-attention kernel reads
natively AND the layout whose per-token cache insert is a single
scatter with fully-contiguous [2*Hkv, D] windows at a leading
(page, offset) index — the earlier split-K/V, heads-leading layout put
the scatter window across the major axis, and the 48 resulting strided
scatters per decode step cost ~3x the model's matmuls (measured on
v5e: 22ms of a 28ms step).

Two execution paths, chosen statically at trace time:

- TPU: the pallas ragged-paged-attention kernel
  (jax.experimental.pallas.ops.tpu.ragged_paged_attention) —
  block-table-indexed async DMA of pages into VMEM with online softmax,
  so HBM traffic per step is the *live* KV only.  This is the kernel
  class the reference's serving stack reaches through vLLM's TPU
  backend (reference: python/ray/llm/_internal/serve/engines/vllm/).
- elsewhere (CPU tests): an exact jnp path that gathers pages and does
  dense masked attention — numerically the spec for the kernel.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def combine_kv(k, v):
    """Interleave per-head K and V ([..., Hkv, D] each) into the
    combined-head layout [..., 2*Hkv, D] the kernel reads."""
    stacked = jnp.stack([k, v], axis=-2)          # [..., Hkv, 2, D]
    shape = k.shape[:-2] + (2 * k.shape[-2], k.shape[-1])
    return stacked.reshape(shape)


def paged_decode_attention(q, kv_pages, block_table, seq_lens,
                           page_size: int):
    """One decode step of attention over the paged cache.

    q: [B, H, D] (one new token per slot); kv_pages:
    [NP, page, 2*Hkv, D] combined; block_table: [B, P] page ids;
    seq_lens: [B] sequence length INCLUDING the new token.
    Returns [B, H, D].
    """
    from .attention import _on_tpu
    if _on_tpu():
        return _ragged_path(q, kv_pages, block_table, seq_lens)
    return _exact_path(q, kv_pages, block_table, seq_lens, page_size)


def _ragged_path(q, kv_pages, block_table, seq_lens):
    from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
        ragged_paged_attention)

    B, H, D = q.shape
    # Decode is the all-sequences-length-1 case of the ragged layout:
    # query token i belongs to sequence i.
    cu_q_lens = jnp.arange(B + 1, dtype=jnp.int32)
    num_seqs = jnp.array([B], jnp.int32)
    out = ragged_paged_attention(
        q, kv_pages,
        kv_lens=seq_lens.astype(jnp.int32),
        page_indices=block_table.astype(jnp.int32),
        cu_q_lens=cu_q_lens, num_seqs=num_seqs,
        sm_scale=1.0 / math.sqrt(D),
        # The auto-tuned block sizes overshoot the 16M scoped-vmem
        # default by a hair on v5e at decode shapes; v5e has 128M VMEM.
        vmem_limit_bytes=64 * 1024 * 1024)
    return out.astype(q.dtype)


def _exact_path(q, kv_pages, block_table, seq_lens, page_size: int):
    """Reference semantics: gather each sequence's pages and run dense
    masked attention.  Materializes [B, H, S_max, D] — fine for CPU
    tests, never the TPU path."""
    B, H, D = q.shape
    Hkv = kv_pages.shape[2] // 2
    P = block_table.shape[1]
    group = H // Hkv
    pages = jnp.take(kv_pages, block_table, axis=0)  # [B, P, page, 2Hkv, D]
    k = pages[:, :, :, 0::2, :]                      # [B, P, page, Hkv, D]
    v = pages[:, :, :, 1::2, :]
    k = k.reshape(B, P * page_size, Hkv, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, P * page_size, Hkv, D).transpose(0, 2, 1, 3)
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(D)
    kv_pos = jnp.arange(P * page_size)
    mask = kv_pos[None, :] < seq_lens[:, None]          # [B, S_max]
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhk,bhkd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
