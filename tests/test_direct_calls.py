"""Direct actor-call paths: driver fast path + worker->worker channels.

Reference analog: the caller->actor submission stream tests around
src/ray/core_worker/task_submission/actor_task_submitter.h:68 and
python/ray/tests/test_actor.py ordering/failure semantics — here the
driver pushes pre-encoded frames to the bound worker
(runtime.submit_actor_direct) and worker callers push over authenticated
per-process channels (_private/direct.py), with the head only involved
for resolution, restarts, and escaped results.
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def rt(ray_start_isolated):
    yield ray_start_isolated


@ray_tpu.remote
class Sink:
    def __init__(self):
        self.log = []

    def push(self, caller, i):
        self.log.append((caller, i))
        return len(self.log)

    def get_log(self):
        return list(self.log)


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return x * 2


class TestDriverDirectPath:
    def test_ordered_results(self, rt):
        s = Sink.remote()
        refs = [s.push.remote("d", i) for i in range(100)]
        assert ray_tpu.get(refs) == list(range(1, 101))
        assert [i for _, i in ray_tpu.get(s.get_log.remote())] == \
            list(range(100))

    def test_uses_direct_inflight_registry(self, rt):
        s = Sink.remote()
        ray_tpu.get(s.push.remote("d", 0))
        # After a call completes the registry must be drained (no leak).
        assert not rt._direct_inflight

    def test_error_propagates_with_message(self, rt):
        @ray_tpu.remote
        class Bad:
            def boom(self):
                raise ValueError("intentional-direct")

        b = Bad.remote()
        with pytest.raises(Exception, match="intentional-direct"):
            ray_tpu.get(b.boom.remote())
        from ray_tpu.util import state as state_api
        time.sleep(0.1)
        failed = state_api.list_tasks(filters=[("state", "=", "FAILED")])
        assert any("intentional-direct" in (t["error_message"] or "")
                   for t in failed)

    def test_state_api_sees_direct_calls(self, rt):
        s = Sink.remote()
        ray_tpu.get([s.push.remote("d", i) for i in range(10)])
        from ray_tpu.util import state as state_api
        time.sleep(0.1)
        rows = [t for t in state_api.list_tasks()
                if t.get("type") == "ACTOR_TASK"
                and t["state"] == "FINISHED"
                and t["name"].startswith("Sink.push")]
        assert len(rows) >= 10

    def test_inflight_fails_on_worker_death(self, rt):
        @ray_tpu.remote
        class Mortal:
            def die(self):
                import os
                os._exit(1)

        m = Mortal.remote()
        ref = m.die.remote()
        with pytest.raises(Exception):
            ray_tpu.get(ref, timeout=20)
        assert not rt._direct_inflight


class TestWorkerChannels:
    def test_per_caller_order_across_concurrent_callers(self, rt):
        s = Sink.remote()
        ray_tpu.get(s.get_log.remote())

        @ray_tpu.remote
        def caller(s, name, n):
            return ray_tpu.get([s.push.remote(name, i) for i in range(n)])

        ray_tpu.get([caller.remote(s, f"w{j}", 40) for j in range(3)])
        log = ray_tpu.get(s.get_log.remote())
        assert len(log) == 120
        for j in range(3):
            assert [i for c, i in log if c == f"w{j}"] == list(range(40))

    def test_channel_actually_used(self, rt):
        s = Sink.remote()
        ray_tpu.get(s.get_log.remote())

        @ray_tpu.remote
        def probe(s):
            from ray_tpu._private.runtime import current_runtime
            wr = current_runtime()
            ray_tpu.get(s.push.remote("p", 0))
            chans = getattr(wr, "_channels", {})
            return [c.state for c in chans.values()]

        assert ray_tpu.get(probe.remote(s)) == ["OPEN"]

    def test_escaped_result_resolves_anywhere(self, rt):
        s = Sink.remote()
        d = Doubler.remote()
        ray_tpu.get([s.get_log.remote(), d.double.remote(1)])

        @ray_tpu.remote
        def chained(s, d):
            r1 = s.push.remote("c", 1)       # direct; caller-local result
            r2 = d.double.remote(r1)         # escapes -> promoted upstream
            return ray_tpu.get(r2)

        assert ray_tpu.get(chained.remote(s, d)) == 2

    def test_crash_then_restart_recovers(self, rt):
        @ray_tpu.remote
        class Fragile:
            def ping(self):
                return "ok"

            def die(self):
                import os
                os._exit(1)

        f = Fragile.options(max_restarts=1).remote()
        ray_tpu.get(f.ping.remote())

        @ray_tpu.remote
        def crash_caller(f):
            try:
                ray_tpu.get(f.die.remote(), timeout=10)
                return "no-error"
            except Exception as e:
                err = type(e).__name__
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    return err + ":" + ray_tpu.get(f.ping.remote(),
                                                   timeout=5)
                except Exception:
                    time.sleep(0.3)
            return err + ":no-recovery"

        res = ray_tpu.get(crash_caller.remote(f))
        assert res == "ActorError:ok", res

    def test_large_result_via_upstream_registration(self, rt):
        import numpy as np

        @ray_tpu.remote
        class Big:
            def blob(self):
                return np.ones((512, 512), np.float64)  # > inline cutoff

        b = Big.remote()
        ray_tpu.get(b.blob.remote())

        @ray_tpu.remote
        def reader(b):
            arr = ray_tpu.get(b.blob.remote())
            return float(arr.sum())

        assert ray_tpu.get(reader.remote(b)) == 512.0 * 512.0


@ray_tpu.remote
class XCounter:
    def __init__(self):
        self.v = 0

    def bump(self, k):
        self.v += k
        return self.v

    def ready(self):
        return "up"

    def die(self):
        import os
        os._exit(1)


def _bump_event_count(runtime):
    return sum(1 for t in runtime.events.snapshot(None, 100000)
               if "XCounter.bump" in (t.get("name") or ""))


@pytest.mark.slow
class TestCrossNodeDirect:
    """Direct submission as the CLUSTER default path (reference:
    normal_task_submitter.cc:516 / actor_task_submitter.h:68 push the
    call caller->executor across the cluster): worker->worker channels
    between nodes, the driver's own channel to remote actors, and
    per-node credited pipelining — each proven by the head seeing no
    per-call traffic."""

    def test_worker_to_worker_across_nodes(self):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1, resources={"A": 1})
            c.add_node(num_cpus=1, resources={"B": 1})
            actor = XCounter.options(resources={"B": 0.1}).remote()
            assert ray_tpu.get(actor.ready.remote()) == "up"

            @ray_tpu.remote(resources={"A": 0.1})
            def caller(a, n):
                vals = ray_tpu.get([a.bump.remote(1) for _ in range(n)])
                from ray_tpu._private.runtime import current_runtime
                wr = current_runtime()
                states = [ch.state for ch in
                          getattr(wr, "_channels", {}).values()]
                return vals, states

            before = _bump_event_count(c.runtime)
            vals, states = ray_tpu.get(caller.remote(actor, 50))
            assert vals == list(range(1, 51))
            # The calls rode the caller's cross-node channel: OPEN on the
            # caller, and the head recorded no per-call task events.
            assert states == ["OPEN"]
            assert _bump_event_count(c.runtime) == before

    def test_worker_channel_survives_actor_restart_across_nodes(self):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1, resources={"A": 1})
            c.add_node(num_cpus=1, resources={"B": 1})
            actor = XCounter.options(resources={"B": 0.1},
                                     max_restarts=1).remote()
            assert ray_tpu.get(actor.ready.remote()) == "up"

            @ray_tpu.remote(resources={"A": 0.1})
            def crash_caller(a):
                assert ray_tpu.get(a.bump.remote(1)) == 1
                try:
                    ray_tpu.get(a.die.remote(), timeout=15)
                    return "no-error"
                except Exception as e:
                    err = type(e).__name__
                deadline = time.time() + 40
                while time.time() < deadline:
                    try:
                        v = ray_tpu.get(a.bump.remote(5), timeout=5)
                        return f"{err}:{v}"
                    except Exception:
                        time.sleep(0.3)
                return err + ":no-recovery"

            # Channel breaks mid-call, re-resolves to the restarted
            # worker, and the fresh incarnation starts from 0.
            assert ray_tpu.get(crash_caller.remote(actor)) == "ActorError:5"

    def test_driver_channel_to_remote_actor(self):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1)
            actor = XCounter.options(max_restarts=1).remote()
            assert ray_tpu.get(actor.ready.remote()) == "up"
            before = _bump_event_count(c.runtime)
            vals = ray_tpu.get([actor.bump.remote(1) for _ in range(60)])
            assert vals == list(range(1, 61))
            ast = c.runtime._actor_state(actor._actor_id)
            assert ast.driver_mode == "direct"
            assert ast.driver_ch is not None and \
                ast.driver_ch.state == "OPEN"
            # Per-call traffic never crossed the head's control plane.
            assert _bump_event_count(c.runtime) == before

    def test_driver_channel_survives_restart_then_kill(self):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1)
            actor = XCounter.options(max_restarts=1).remote()
            assert ray_tpu.get(actor.ready.remote()) == "up"
            assert ray_tpu.get(actor.bump.remote(2)) == 2
            with pytest.raises(Exception):
                ray_tpu.get(actor.die.remote(), timeout=15)
            deadline = time.time() + 40
            v = None
            while time.time() < deadline:
                try:
                    v = ray_tpu.get(actor.bump.remote(3), timeout=5)
                    break
                except Exception:
                    time.sleep(0.3)
            assert v == 3  # restarted incarnation, fresh state
            ray_tpu.kill(actor)
            with pytest.raises(Exception):
                ray_tpu.get(actor.bump.remote(1), timeout=20)

    def test_remote_pipelining_with_credits(self):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1)

            @ray_tpu.remote
            def f(i):
                return i * 2

            refs = [f.remote(i) for i in range(40)]
            assert ray_tpu.get(refs) == [i * 2 for i in range(40)]
            # All credits returned once the burst drains.
            assert sum(c.runtime._pipeline_credits.values()) == 0

    def test_pipeline_reject_resubmits(self, monkeypatch):
        from ray_tpu.cluster_utils import Cluster
        with Cluster(head_num_cpus=0) as c:
            c.add_node(num_cpus=1)
            # Credits far above the node's queue room force the node to
            # answer UpPipelineReject for the overflow; the head must
            # resubmit those through booked scheduling without loss.
            monkeypatch.setattr(type(c.runtime), "_pipeline_cap",
                                lambda self, nid: 64)

            @ray_tpu.remote
            def g(i):
                time.sleep(0.02)
                return i + 1

            refs = [g.remote(i) for i in range(60)]
            assert ray_tpu.get(refs, timeout=120) == \
                [i + 1 for i in range(60)]
            assert sum(c.runtime._pipeline_credits.values()) == 0
