"""Node providers: how the autoscaler actually gets machines.

Reference analog: NodeProvider implementations under
python/ray/autoscaler/_private/ (aws/gcp/kuberay/local/fake_multi_node).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional


class NodeProvider(ABC):
    """Minimal provider surface (reference: node_provider.py ABC)."""

    @abstractmethod
    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        """Launch a node that joins the cluster; returns a provider id."""

    @abstractmethod
    def terminate_node(self, provider_id: str) -> None:
        ...

    @abstractmethod
    def non_terminated_nodes(self) -> List[str]:
        ...


class LocalSubprocessProvider(NodeProvider):
    """Boots NodeServer processes on this host (the reference's
    FakeMultiNodeProvider pattern — real join path, fake machines).

    ``boot_delay_s`` models the spot-market truth that capacity takes
    time to arrive: ``create_node`` returns a provider id immediately
    (the request is accepted) but the actual process spawns only after
    the delay — the window where a pre-buy-at-notice-time beats a
    buy-after-death by exactly the drain deadline.
    """

    def __init__(self, head_address, token: bytes,
                 boot_delay_s: float = 0.0):
        self._head = head_address
        self._token = token
        self.boot_delay_s = boot_delay_s
        self._lock = threading.Lock()
        # pid -> Popen once spawned; None while the boot delay runs.
        self._procs: Dict[str, Optional[subprocess.Popen]] = {}
        self._timers: Dict[str, threading.Timer] = {}
        self._next = 0

    def _spawn(self, pid: str, cmd: List[str]) -> None:
        with self._lock:
            if pid not in self._procs:
                return  # terminated while still queued
            self._timers.pop(pid, None)
            self._procs[pid] = subprocess.Popen(cmd,
                                                start_new_session=True)

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        import json
        res = dict(resources)
        num_cpus = res.pop("CPU", 0)
        num_tpus = int(res.pop("TPU", 0))
        host, port = self._head
        cmd = [sys.executable, "-m", "ray_tpu._private.node_server_main",
               "--address", f"{host}:{port}",
               "--token", self._token.decode(),
               "--num-cpus", str(num_cpus), "--num-tpus", str(num_tpus)]
        if res:
            cmd += ["--resources", json.dumps(res)]
        with self._lock:
            self._next += 1
            pid = f"{node_type}-{self._next}"
            self._procs[pid] = None
        if self.boot_delay_s > 0:
            t = threading.Timer(self.boot_delay_s, self._spawn,
                                args=(pid, cmd))
            t.daemon = True
            with self._lock:
                self._timers[pid] = t
            t.start()
        else:
            self._spawn(pid, cmd)
        return pid

    def terminate_node(self, provider_id: str) -> None:
        import signal
        with self._lock:
            timer = self._timers.pop(provider_id, None)
            proc = self._procs.pop(provider_id, None)
        if timer is not None:
            timer.cancel()
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, OSError):
                proc.kill()
            proc.wait(timeout=10)

    def lose_instance(self, provider_id: str) -> None:
        """The cloud takes the host away with NO runtime signal (the
        un-noticed spot reclaim): same SIGKILL as terminate, kept as a
        distinct verb so chaos schedules read like the cloud acts."""
        self.terminate_node(provider_id)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            # A node still inside its boot delay is live capacity-in-
            # flight (the request was accepted), not a dead node.
            return [pid for pid, p in self._procs.items()
                    if p is None or p.poll() is None]

    def node_os_pid(self, provider_id: str) -> Optional[int]:
        with self._lock:
            proc = self._procs.get(provider_id)
        return proc.pid if proc is not None else None

    def shutdown(self) -> None:
        with self._lock:
            pids = list(self._procs)
        for pid in pids:
            self.terminate_node(pid)


class TPUPodProvider(NodeProvider):
    """GKE/QueuedResources-shaped provider seam for real TPU fleets.

    Launching a TPU pod slice means submitting a queued-resource request
    (gcloud alpha compute tpus queued-resources create ...) whose VMs run
    ``ray-tpu start --address=<head>`` on boot.  This build environment has
    no GCP access, so the provider shells out to a configurable command
    template and otherwise raises a clear error — the Autoscaler logic
    above it is fully exercised through LocalSubprocessProvider.
    """

    def __init__(self, create_cmd: Optional[str] = None,
                 delete_cmd: Optional[str] = None):
        self._create_cmd = create_cmd
        self._delete_cmd = delete_cmd
        self._nodes: List[str] = []
        self._next = 0

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        if not self._create_cmd:
            raise NotImplementedError(
                "TPUPodProvider needs create_cmd/delete_cmd templates "
                "(e.g. gcloud queued-resources create); use "
                "LocalSubprocessProvider for single-host clusters")
        self._next += 1
        pid = f"{node_type}-{self._next}"
        subprocess.run(self._create_cmd.format(node_id=pid,
                                               node_type=node_type),
                       shell=True, check=True)
        self._nodes.append(pid)
        return pid

    def terminate_node(self, provider_id: str) -> None:
        if self._delete_cmd:
            subprocess.run(self._delete_cmd.format(node_id=provider_id),
                           shell=True, check=False)
        if provider_id in self._nodes:
            self._nodes.remove(provider_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)
