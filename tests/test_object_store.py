"""Native C++ arena object store tests (ray_tpu/_native/store.cc).

Covers the plasma-equivalent surface (reference:
src/ray/object_manager/plasma/store.h:55, eviction_policy.cc,
raylet/local_object_manager.h:46 spill/restore): allocation, seal, zero-copy
reads, LRU spill + restore, pinning, and the end-to-end worker path where
large task results travel through the arena.
"""

import numpy as np
import pytest

from ray_tpu._native import load_store_library
from ray_tpu._private.ids import JobID, ObjectID, TaskID
from ray_tpu._private.object_store import (ArenaReader, NativeArenaStore,
                                           ObjectStoreFullError)

pytestmark = pytest.mark.skipif(load_store_library() is None,
                                reason="no C++ toolchain")


def _oid(i: int) -> ObjectID:
    return ObjectID.of(TaskID.for_driver(JobID.next()), i)


@pytest.fixture
def store(tmp_path):
    s = NativeArenaStore(capacity_bytes=1 << 20,
                         spill_dir=str(tmp_path / "spill"))
    yield s
    s.shutdown()


class TestArenaStore:
    def test_put_get_roundtrip(self, store):
        oid = _oid(1)
        arr = np.arange(1000, dtype=np.float64)
        store.put(oid, {"x": arr, "tag": "hello"})
        out = store.get(oid)
        assert out["tag"] == "hello"
        np.testing.assert_array_equal(out["x"], arr)

    def test_zero_copy_read(self, store):
        oid = _oid(2)
        arr = np.arange(4096, dtype=np.uint8)
        store.put(oid, arr)
        out = store.get(oid)
        # The deserialized array must view arena memory, not a copy.
        assert not out.flags["OWNDATA"]

    def test_cross_process_reader_mapping(self, store):
        oid = _oid(3)
        arr = np.arange(512, dtype=np.int32)
        store.put(oid, arr)
        desc = store.descriptor(oid)
        assert desc[0] == "shma"
        value, _keepalive = ArenaReader.read(desc)
        np.testing.assert_array_equal(value, arr)

    def test_lru_spill_and_restore(self, store):
        big = np.zeros(300_000, dtype=np.uint8)
        oids = [_oid(10 + i) for i in range(4)]
        for i, oid in enumerate(oids):
            store.put(oid, big + i)
        # 4 x ~300KB > 1MB: the earliest objects must have spilled.
        stats = store.stats()
        assert stats["num_spilled"] >= 1
        assert stats["num_objects"] == 4
        # Restoring the coldest object works and round-trips bytes.
        out = store.get(oids[0])
        assert out[0] == 0 and out.shape == big.shape
        assert store.stats()["num_restored"] >= 1

    def test_pinned_objects_never_evict(self, store):
        pinned_oid = _oid(20)
        store.put(pinned_oid, np.ones(300_000, dtype=np.uint8))
        desc = store.pin_desc_by_key(pinned_oid.binary())
        assert desc is not None
        # Fill the arena; the pinned object must survive in memory.
        for i in range(4):
            store.put(_oid(21 + i), np.zeros(200_000, dtype=np.uint8))
        stats = store.stats()
        assert stats["num_pinned"] == 1
        fresh = store.pin_desc_by_key(pinned_oid.binary())
        assert fresh[2] == desc[2]  # same offset: it never moved
        store.unpin_key(pinned_oid.binary())
        store.unpin_key(pinned_oid.binary())

    def test_arena_full_of_pins_raises(self, store):
        oid = _oid(30)
        store.put(oid, np.zeros(600_000, dtype=np.uint8))
        assert store.pin_desc_by_key(oid.binary()) is not None
        with pytest.raises(ObjectStoreFullError):
            store.allocate(_oid(31), 600_000)
        store.unpin_key(oid.binary())

    def test_delete_frees_space(self, store):
        oid = _oid(40)
        store.put(oid, np.zeros(600_000, dtype=np.uint8))
        used = store.stats()["used_bytes"]
        store.delete(oid)
        assert store.stats()["used_bytes"] < used
        assert not store.contains(oid)
        # Freed space is reusable immediately.
        store.put(_oid(41), np.zeros(900_000, dtype=np.uint8))

    def test_descriptor_refresh_after_restore(self, store):
        """Spilled objects may restore at a new offset; pin_desc refreshes."""
        a, b = _oid(50), _oid(51)
        store.put(a, np.zeros(400_000, dtype=np.uint8))
        first = store.descriptor(a)
        store.put(b, np.zeros(500_000, dtype=np.uint8))
        # Force a out, then b out, then a back in at (likely) a new offset.
        store.put(_oid(52), np.zeros(500_000, dtype=np.uint8))
        fresh = store.pin_desc_by_key(a.binary())
        assert fresh is not None
        value = store.read_by_key(a.binary(), pin=False)
        assert value.nbytes == 400_000
        store.unpin_key(a.binary())
        assert first[0] == "shma"


class TestArenaEndToEnd:
    """Large values flowing driver <-> workers through the arena."""

    def test_large_task_args_and_results(self, ray_start):
        import ray_tpu

        arr = np.random.default_rng(0).standard_normal(200_000)

        @ray_tpu.remote
        def double(x):
            return x * 2.0

        ref = double.remote(ray_tpu.put(arr))
        np.testing.assert_allclose(ray_tpu.get(ref), arr * 2.0)

    def test_actor_retains_large_state(self, ray_start):
        import ray_tpu

        @ray_tpu.remote
        class Holder:
            def __init__(self, x):
                self.x = x

            def total(self):
                return float(self.x.sum())

        arr = np.ones(300_000)
        h = Holder.remote(ray_tpu.put(arr))
        assert ray_tpu.get(h.total.remote()) == pytest.approx(300_000.0)
        # Repeated calls keep reading the retained (pinned) state.
        assert ray_tpu.get(h.total.remote()) == pytest.approx(300_000.0)

    def test_worker_to_worker_large_transfer(self, ray_start):
        import ray_tpu

        @ray_tpu.remote
        def produce():
            return np.full(250_000, 7.0)

        @ray_tpu.remote
        def consume(x):
            return float(x.sum())

        assert ray_tpu.get(consume.remote(produce.remote())) == \
            pytest.approx(250_000 * 7.0)
