"""Thin compat shim over ``ray_tpu.checkpoint``.

The checkpoint implementation moved into the first-class
``ray_tpu/checkpoint/`` subsystem (async sharded saves, atomic manifest
commit, resharding restore, emergency replicas).  This module keeps the
historical import surface — ``Checkpoint``, ``CheckpointManager``,
``save_pytree``, ``load_pytree`` — stable for existing train code.
"""

from __future__ import annotations

from ..checkpoint.format import load_pytree, save_pytree
from ..checkpoint.manager import Checkpoint, CheckpointManager

__all__ = ["Checkpoint", "CheckpointManager", "save_pytree", "load_pytree"]
