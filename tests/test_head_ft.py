"""Head fault tolerance: kill -9 the head process, restart it, and the
persisted control plane comes back — named actors restart from their
creation specs, placement groups re-plan, the KV store survives.

Reference analog: GCS fault tolerance — persistent store + GcsInitData
replay + raylet reconnect (src/ray/gcs/gcs_server.cc:164-189,
gcs_init_data.h); python/ray/tests/test_gcs_fault_tolerance.py is the
reference's test of the same contract.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

HEAD_BOOT_TIMEOUT = 60


def _start_head(tmp_path, state_dir, token="a" * 32):
    addr_file = os.path.join(tmp_path, "head_address")
    try:
        os.unlink(addr_file)  # a SIGKILLed head leaves its stale file
    except FileNotFoundError:
        pass
    env = dict(os.environ)
    env.pop("RAY_TPU_CONFIG_BLOB", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.head",
         "--port", "0", "--node-port", "0",
         "--token", token,
         "--address-file", addr_file,
         "--dashboard-port", "-1",
         "--state-dir", state_dir,
         "--num-cpus", "4", "--num-tpus", "0"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + HEAD_BOOT_TIMEOUT
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"head exited early rc={proc.returncode}")
        try:
            with open(addr_file) as f:
                info = json.load(f)
            return proc, info
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("head did not boot")


def _connect(info, token="a" * 32):
    import ray_tpu
    return ray_tpu.init(address=info["node_address"],
                        cluster_token=token.encode())


@pytest.fixture
def head_env(tmp_path):
    state_dir = str(tmp_path / "state")
    procs = []

    def start():
        proc, info = _start_head(str(tmp_path), state_dir)
        procs.append(proc)
        return proc, info

    yield start
    import ray_tpu
    ray_tpu.shutdown()
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=10)


class TestHeadFaultTolerance:
    def test_kill9_restart_actors_pgs_kv_survive(self, head_env):
        import ray_tpu

        proc, info = head_env()
        _connect(info)

        @ray_tpu.remote(name="survivor", max_restarts=0, num_cpus=0)
        class Counter:
            def __init__(self, base):
                self.base = base
                self.n = 0

            def bump(self):
                self.n += 1
                return self.base + self.n

        c = Counter.remote(100)
        assert ray_tpu.get(c.bump.remote(), timeout=60) == 101

        pg = ray_tpu.placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.ready(timeout=30)

        from ray_tpu._private.api import _control
        _control("kv_put", "ft-key", b"ft-value")

        # Hard-kill the head: no shutdown hooks run, only the WAL remains.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=15)
        ray_tpu.shutdown()

        # Restart with the same state dir; replay revives the control
        # plane.
        proc2, info2 = head_env()
        _connect(info2)

        # KV survived.
        assert _control("kv_get", "ft-key") == b"ft-value"

        # The named actor restarted from its creation spec (fresh state:
        # counter resets, constructor args replayed).
        deadline = time.monotonic() + 60
        while True:
            try:
                h = ray_tpu.get_actor("survivor")
                v = ray_tpu.get(h.bump.remote(), timeout=30)
                assert v == 101, v
                break
            except (ValueError, ray_tpu.ActorError):
                if time.monotonic() > deadline:
                    pytest.fail(
                        "named actor did not come back after head restart")
                time.sleep(0.5)

        # The placement group was re-planned and is CREATED again.
        from ray_tpu.util.state import list_placement_groups
        pgs = {p["placement_group_id"]: p
               for p in list_placement_groups()}
        assert pg.id.hex() in pgs
        assert pgs[pg.id.hex()]["state"] == "CREATED"

    def test_wal_snapshot_roundtrip(self, tmp_path):
        from ray_tpu._private.persist import StateStore

        d = str(tmp_path / "s")
        st = StateStore(d)
        st.append(("kv_put", "default", "a", b"1"))
        st.append(("kv_put", "default", "b", b"2"))
        st.append(("kv_del", "default", "a"))
        st.close()

        st2 = StateStore(d)
        recs = st2.load()
        assert recs == [("kv_put", "default", "a", b"1"),
                        ("kv_put", "default", "b", b"2"),
                        ("kv_del", "default", "a")]
        st2.compact([("kv_put", "default", "b", b"2")])
        st2.append(("kv_put", "default", "c", b"3"))
        st2.close()

        st3 = StateStore(d)
        assert st3.load() == [("kv_put", "default", "b", b"2"),
                              ("kv_put", "default", "c", b"3")]
        st3.close()

    def test_torn_tail_is_ignored(self, tmp_path):
        from ray_tpu._private.persist import StateStore

        d = str(tmp_path / "s")
        st = StateStore(d)
        st.append(("kv_put", "default", "a", b"1"))
        st.close()
        # Simulate a mid-write kill: garbage half-record at the tail.
        with open(os.path.join(d, "wal.bin"), "ab") as f:
            f.write(b"\xff\xff\x00\x00partial")
        st2 = StateStore(d)
        assert st2.load() == [("kv_put", "default", "a", b"1")]
        st2.close()
