"""Cross-host decode replicas: the fleet's p2p prefill handoff path.

Same-host handoff is zero-copy through the shm object store; a replica
on ANOTHER node instead receives its handoffs through the normal
object-transfer plane: the dispatcher ``ray_tpu.put``s the
:class:`~ray_tpu.llm.disagg.KVHandoff` once and passes the ref to the
replica actor — argument materialization on the remote node pulls the
blob over the DataServer's p2p path and records the existing
``ray_tpu_store_transfer_bytes_total{direction="pull"}`` /
``..._seconds{op}`` series, so KV shipping shows up in the data-plane
telescope with zero new transfer code.

Two classes:

* :class:`ReplicaHost` — the actor body: owns a
  :class:`~ray_tpu.llm.fleet.replica.DecodeReplica` and buffers its
  finishes for the handle to drain (callbacks can't cross processes).
* :class:`RemoteReplica` — the FleetServer-side handle, duck-typed to
  DecodeReplica's router surface (``accepting`` / ``import_prefill`` /
  ``try_serve_cached`` / ``load_stats`` / ``summary`` / ``drain`` /
  ``kill``): a poll thread drains finishes into the fleet's normal
  ``on_finish`` callback and refreshes a cached load/summary snapshot
  so routing never blocks on a cross-host RPC.
"""

from __future__ import annotations

import threading
import time
import weakref
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Sequence

from ..._private import sanitizer
from .replica import (DecodeReplica, STATE_ACTIVE, STATE_DEAD,
                      STATE_DRAINING)


class ReplicaHost:
    """Actor body hosting one DecodeReplica on its placement node."""

    def __init__(self, build_params, name: str,
                 engine_options: Optional[Dict[str, Any]] = None,
                 cache_capacity_bytes: int = 64 * 1024 * 1024,
                 record_token_times: bool = False):
        self._lock = threading.Lock()
        self._finished: List[Dict[str, Any]] = []
        self._replica = DecodeReplica(
            build_params, name=name, engine_options=engine_options,
            cache_capacity_bytes=cache_capacity_bytes,
            record_token_times=record_token_times,
            on_finish=self._buffer)

    def _buffer(self, _replica, req) -> None:
        with self._lock:
            self._finished.append({
                "rid": req.request_id,
                "output_tokens": list(req.output_tokens),
                "finish_reason": req.finish_reason,
                # perf_counter stamps are process-local: ship the deltas
                # (ITL) — absolute TTFT doesn't survive the host hop.
                "itl_s": [b - a for a, b in zip(req.token_times,
                                                req.token_times[1:])],
            })

    def import_prefill(self, handoff, retain: bool = True
                       ) -> Optional[int]:
        return self._replica.import_prefill(handoff, retain=retain)

    def try_serve_cached(self, prompt_tokens, params,
                         t_submit: float = 0.0) -> Optional[int]:
        # t_submit is the CALLER's clock; replay against our own so the
        # engine's TTFT math stays monotonic.
        return self._replica.try_serve_cached(
            prompt_tokens, params, t_submit=time.perf_counter())

    def cancel(self, rid: int) -> None:
        self._replica.cancel(rid)

    def snapshot(self) -> Dict[str, Any]:
        return {"load": self._replica.load_stats(),
                "summary": self._replica.summary(),
                "state": self._replica.state,
                "idle": self._replica.idle()}

    def drain_finished(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = self._finished
            self._finished = []
        return out

    def drain(self) -> None:
        self._replica.drain()

    def kill(self) -> List[int]:
        return self._replica.kill()


class RemoteReplica:
    """FleetServer-side handle for a replica actor on another node."""

    def __init__(self, build_params, *, name: str,
                 engine_options: Optional[Dict[str, Any]] = None,
                 cache_capacity_bytes: int = 64 * 1024 * 1024,
                 record_token_times: bool = False,
                 on_finish=None, num_cpus: int = 1,
                 poll_interval_s: float = 0.02):
        import ray_tpu

        self._ray = ray_tpu
        self.name = name
        self._on_finish = on_finish
        self._actor = ray_tpu.remote(num_cpus=num_cpus)(
            ReplicaHost).remote(
                build_params, name, engine_options,
                cache_capacity_bytes, record_token_times)
        self._state = STATE_ACTIVE
        self._snap: Dict[str, Any] = {"load": {}, "summary": None,
                                      "idle": False}
        self._snap_lock = threading.Lock()
        #: One put per handoff object even across import retries — the
        #: dispatcher re-attempts the same object under backpressure and
        #: re-shipping megabytes per retry would swamp the p2p plane.
        #: Keyed by a WEAK reference to the handoff (not id()): once a
        #: handoff is garbage-collected its weakref goes dead and can
        #: never compare equal to a new object, so an address-reuse
        #: collision can't ship a stale ref.
        self._put_cache: tuple = (None, None)  # (weakref, ObjectRef)
        self._stop = threading.Event()
        self._poll = poll_interval_s
        self._poller = sanitizer.spawn(
            self._poll_loop, name=f"fleet-remote-{name}")

    # -- router surface (DecodeReplica-compatible) --------------------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def accepting(self) -> bool:
        return self._state == STATE_ACTIVE

    def _handoff_ref(self, handoff):
        cached_wr, ref = self._put_cache
        if cached_wr is None or cached_wr() is not handoff:
            ref = self._ray.put(handoff)
            self._put_cache = (weakref.ref(handoff), ref)
        return ref

    def import_prefill(self, handoff, retain: bool = True
                       ) -> Optional[int]:
        if not self.accepting:
            return None
        try:
            return self._ray.get(self._actor.import_prefill.remote(
                self._handoff_ref(handoff), retain))
        except Exception:
            self._state = STATE_DEAD
            return None

    def try_serve_cached(self, prompt_tokens: Sequence[int], params,
                         t_submit: float = 0.0) -> Optional[int]:
        if not self.accepting or params.temperature > 0.0:
            return None
        with self._snap_lock:
            summ = self._snap.get("summary")
        if not summ:
            return None
        try:
            return self._ray.get(self._actor.try_serve_cached.remote(
                list(prompt_tokens), params, t_submit))
        except Exception:
            self._state = STATE_DEAD
            return None

    def cancel(self, rid: int) -> None:
        try:
            self._actor.cancel.remote(rid)  # ray-tpu: detached
        except Exception:
            pass

    def load_stats(self) -> Dict[str, Any]:
        with self._snap_lock:
            load = dict(self._snap.get("load") or {})
        load.setdefault("name", self.name)
        load.setdefault("state", self._state)
        load.setdefault("ongoing", 0)
        load.setdefault("kv_occupancy", 0.0)
        load.setdefault("waiting", 0)
        return load

    def summary(self):
        with self._snap_lock:
            return self._snap.get("summary")

    def idle(self) -> bool:
        with self._snap_lock:
            return bool(self._snap.get("idle"))

    @property
    def engine(self):
        """Depth accounting shim: scale_down reads
        ``len(rep.engine.running)``; surface the cached ongoing count
        through the same shape."""
        with self._snap_lock:
            n = int((self._snap.get("load") or {}).get("ongoing", 0))
        return SimpleNamespace(running=list(range(n)))

    # -- poll (finishes + snapshot) -----------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self._poll)
            if self._stop.is_set():
                return
            try:
                done = self._ray.get(self._actor.drain_finished.remote())
                snap = self._ray.get(self._actor.snapshot.remote())
            except Exception:
                # Actor gone (node loss, kill): stop polling; the fleet
                # manager reaps dead replicas and sheds their in-flight.
                self._state = STATE_DEAD
                return
            with self._snap_lock:
                self._snap = snap
            if self._state != STATE_DEAD \
                    and snap.get("state") == STATE_DRAINING:
                self._state = STATE_DRAINING
            for rec in done:
                if self._on_finish is not None:
                    self._on_finish(self, _as_request(rec))

    # -- lifecycle ----------------------------------------------------------

    def drain(self) -> None:
        if self._state == STATE_ACTIVE:
            self._state = STATE_DRAINING
            try:
                self._actor.drain.remote()  # ray-tpu: detached
            except Exception:
                self._state = STATE_DEAD

    def kill(self, timeout_s: float = 5.0) -> List[int]:
        self._state = STATE_DEAD
        self._stop.set()
        self._poller.join(timeout_s)
        lost: List[int] = []
        try:
            lost = self._ray.get(self._actor.kill.remote())
        except Exception:
            pass
        try:
            self._ray.kill(self._actor)
        except Exception:
            pass
        return lost

    close = kill


def _as_request(rec: Dict[str, Any]):
    """Shape one drained finish record like an engine Request for the
    fleet's on_finish callback.  Cross-host TTFT is not reconstructable
    from process-local clocks, so t_submit/t_first stay zero (the
    result carries ttft_s=None) while ITL rides the shipped deltas."""
    times = [0.0]
    for d in rec.get("itl_s") or []:
        times.append(times[-1] + d)
    return SimpleNamespace(
        request_id=rec["rid"],
        output_tokens=rec.get("output_tokens") or [],
        finish_reason=rec.get("finish_reason", ""),
        t_submit=0.0, t_first=0.0,
        token_times=times if len(times) > 1 else [])
