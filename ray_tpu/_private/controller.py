"""Control plane: cluster state tables (the GCS equivalent).

The reference's GCS (reference: src/ray/gcs/gcs_server.h:97) owns node
registry + health (gcs_node_manager.h, gcs_health_check_manager.h:46), the
actor FSM (gcs/actor/gcs_actor_manager.h:94 — REGISTER → PENDING → ALIVE →
RESTARTING/DEAD with max_restarts), placement groups with two-phase bundle
commit (gcs_placement_group_scheduler.h:115), a job table, an internal KV
(gcs_kv_manager.h) and pubsub.  This module is the same control plane as
plain in-process tables behind a lock; the transport seam (every mutation is a
method call) is where a gRPC service drops in for multi-host deployments.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .ids import ActorID, JobID, NodeID, PlacementGroupID
from .protocol import TaskSpec
from .resources import ResourceSet

# Actor FSM states (reference: gcs_actor_manager.h FSM)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"

# Placement group states
PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"


@dataclass
class NodeInfo:
    node_id: NodeID
    hostname: str
    total_resources: ResourceSet
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    is_head: bool = False
    # Drain lifecycle (preemption/maintenance notice): a draining node is
    # unschedulable for new leases and expected to die by the deadline.
    # Monotonic stamps — only comparable inside the head process; readers
    # in other processes get a relative drain_remaining_s via ctl_nodes.
    draining: bool = False
    drain_reason: str = ""
    drain_deadline_mono: float = 0.0
    drain_started_mono: float = 0.0


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    state: str
    creation_spec: Optional[TaskSpec]
    max_restarts: int
    num_restarts: int = 0
    node_id: Optional[NodeID] = None
    death_cause: Optional[str] = None
    namespace: str = "default"
    class_name: str = ""


@dataclass
class BundleInfo:
    index: int
    resources: ResourceSet
    node_id: Optional[NodeID] = None  # committed location


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    name: Optional[str]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundles: List[BundleInfo]
    state: str = PG_PENDING


@dataclass
class JobInfo:
    job_id: JobID
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    entrypoint: str = ""


class Controller:
    """In-process GCS-equivalent state store."""

    def __init__(self):
        self._lock = threading.RLock()
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name)
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.jobs: Dict[JobID, JobInfo] = {}
        self._kv: Dict[str, Dict[str, bytes]] = {}
        # Writers notify blocked kv_wait readers (no poll loops; the
        # reference's pubsub long-poll analog, reference: pubsub/publisher.h).
        self._kv_cond = threading.Condition(self._lock)
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = {}
        # Long-poll pubsub rings (reference: pubsub/publisher.h buffered
        # per-channel delivery to remote subscribers).
        self._pubsub_cond = threading.Condition()
        self._pubsub_rings: Dict[str, List] = {}
        self._pubsub_seq = 0
        self._pubsub_ring_cap = 1000
        # Persisted node identities a restarted head will accept
        # same-identity re-attaches from (node_id bytes -> (hostname,
        # resources dict, num_tpus)); reference: gcs_init_data.h node
        # table driving raylet re-registration after GCS failover.
        self.revivable_nodes: Dict[bytes, tuple] = {}

    # -- nodes --------------------------------------------------------------

    # Optional sink for structured export events (reference:
    # RayEventRecorder / export_*.proto); set by the Runtime to the
    # session's JSONL writer.  Signature: (source_type, event_dict).
    event_sink: Optional[Callable[[str, Dict[str, Any]], None]] = None

    # Optional durable store (persist.StateStore); set by a Runtime started
    # with a state_dir.  Every table mutation appends a replayable record
    # (reference: GCS writing tables through its StoreClient).
    persist: Optional[Any] = None

    def _p(self, record: tuple) -> None:
        store = self.persist
        if store is not None:
            try:
                store.append(record)
            except Exception:  # noqa: BLE001 — persistence must not break
                pass

    def restore(self, records: List[tuple]) -> None:
        """Rebuild tables from a snapshot+WAL record stream (reference:
        GcsInitData::AsyncLoad rebuilding managers on GCS restart).
        Last record per key wins; node records are never persisted (nodes
        re-register), and replayed bundle placements are reset by the
        Runtime before re-planning."""
        with self._lock:
            for r in records:
                kind = r[0]
                if kind == "actor":
                    info = r[1]
                    self.actors[info.actor_id] = info
                    if info.name:
                        self.named_actors[(info.namespace, info.name)] = \
                            info.actor_id
                elif kind == "pg":
                    self.placement_groups[r[1].pg_id] = r[1]
                elif kind == "job":
                    self.jobs[r[1].job_id] = r[1]
                elif kind == "kv_put":
                    self._kv.setdefault(r[1], {})[r[2]] = r[3]
                elif kind == "kv_del":
                    self._kv.get(r[1], {}).pop(r[2], None)
                elif kind == "node_identity":
                    self.revivable_nodes[r[1]] = r[2]
                elif kind == "node_gone":
                    self.revivable_nodes.pop(r[1], None)

    def snapshot_records(self) -> List[tuple]:
        """Full table state as a compact record stream (for WAL
        compaction)."""
        with self._lock:
            out: List[tuple] = []
            for info in self.actors.values():
                out.append(("actor", info))
            for pg in self.placement_groups.values():
                out.append(("pg", pg))
            for job in self.jobs.values():
                out.append(("job", job))
            for ns, kv in self._kv.items():
                for k, v in kv.items():
                    out.append(("kv_put", ns, k, v))
            for nid, ident in self.revivable_nodes.items():
                out.append(("node_identity", nid, ident))
            return out

    def _export(self, source_type: str, event: Dict[str, Any]) -> None:
        sink = self.event_sink
        if sink is not None:
            try:
                sink(source_type, event)
            except Exception:  # noqa: BLE001 — observability must not break
                pass

    def note_revivable(self, node_id_bytes: bytes, ident: tuple) -> None:
        """Persist a node identity for post-restart re-attach (all
        mutations locked: snapshot_records iterates this table)."""
        with self._lock:
            self.revivable_nodes[node_id_bytes] = ident
        self._p(("node_identity", node_id_bytes, ident))

    def drop_revivable(self, node_id_bytes: bytes) -> None:
        with self._lock:
            self.revivable_nodes.pop(node_id_bytes, None)
        self._p(("node_gone", node_id_bytes))

    def get_revivable(self, node_id_bytes: bytes):
        with self._lock:
            return self.revivable_nodes.get(node_id_bytes)

    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self.nodes[info.node_id] = info
        self._export("EXPORT_NODE", {"node_id": info.node_id.hex(),
                                     "state": "ALIVE",
                                     "hostname": info.hostname})
        self.publish("node_added", info)

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            n = self.nodes.get(node_id)
            if n:
                n.last_heartbeat = time.monotonic()

    def drain_node(self, node_id: NodeID, deadline_s: float = 30.0,
                   reason: str = "preemption") -> bool:
        """Mark a node draining: a preemption/maintenance notice arrived
        and the node is expected to disappear within ``deadline_s``.  The
        scheduler side (making it unschedulable) is wired by the Runtime;
        this records the state and fans the event out."""
        now = time.monotonic()
        with self._lock:
            n = self.nodes.get(node_id)
            if not n or not n.alive:
                return False
            already = n.draining
            n.draining = True
            n.drain_reason = reason
            n.drain_started_mono = n.drain_started_mono if already else now
            n.drain_deadline_mono = now + max(0.0, deadline_s)
        if not already:
            self._export("EXPORT_NODE", {"node_id": node_id.hex(),
                                         "state": "DRAINING",
                                         "reason": reason,
                                         "deadline_s": deadline_s})
            self.publish("node_draining", node_id)
        return True

    def undrain_node(self, node_id: NodeID) -> bool:
        """Cancel a drain (notice withdrawn / chaos experiment over)."""
        with self._lock:
            n = self.nodes.get(node_id)
            if not n or not n.draining:
                return False
            n.draining = False
            n.drain_reason = ""
            n.drain_deadline_mono = 0.0
            n.drain_started_mono = 0.0
        self._export("EXPORT_NODE", {"node_id": node_id.hex(),
                                     "state": "ALIVE",
                                     "reason": "undrain"})
        return True

    def draining_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values()
                    if n.alive and n.draining]

    def mark_node_dead(self, node_id: NodeID, reason: str = "") -> None:
        drained_for: Optional[float] = None
        with self._lock:
            n = self.nodes.get(node_id)
            if not n or not n.alive:
                return
            n.alive = False
            if n.draining:
                drained_for = time.monotonic() - n.drain_started_mono
                n.draining = False
        if drained_for is not None:
            # How much of the advertised deadline the cluster actually
            # got between the notice and the node vanishing.
            from ..util import telemetry
            telemetry.observe("ray_tpu_node_drain_seconds", drained_for)
        self._export("EXPORT_NODE", {"node_id": node_id.hex(),
                                     "state": "DEAD", "reason": reason})
        self.publish("node_removed", node_id)

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # -- jobs ---------------------------------------------------------------

    def register_job(self, info: JobInfo) -> None:
        with self._lock:
            self.jobs[info.job_id] = info
        self._p(("job", info))

    def finish_job(self, job_id: JobID) -> None:
        with self._lock:
            j = self.jobs.get(job_id)
            if j:
                j.end_time = time.time()
        if j:
            self._p(("job", j))

    # -- actors -------------------------------------------------------------

    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            self.actors[info.actor_id] = info
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    existing = self.actors.get(self.named_actors[key])
                    if existing and existing.state != DEAD:
                        raise ValueError(
                            f"actor name {info.name!r} already taken in "
                            f"namespace {info.namespace!r}")
                self.named_actors[key] = info.actor_id
        self._p(("actor", info))

    def set_actor_state(self, actor_id: ActorID, state: str,
                        node_id: Optional[NodeID] = None,
                        death_cause: Optional[str] = None) -> None:
        with self._lock:
            a = self.actors.get(actor_id)
            if not a:
                return
            a.state = state
            if node_id is not None:
                a.node_id = node_id
            if death_cause is not None:
                a.death_cause = death_cause
        self._p(("actor", a))
        self._export("EXPORT_ACTOR", {"actor_id": actor_id.hex(),
                                      "state": state,
                                      "death_cause": death_cause})
        self.publish("actor_state", (actor_id, state))

    def get_actor(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> Optional[ActorInfo]:
        with self._lock:
            aid = self.named_actors.get((namespace, name))
            return self.actors.get(aid) if aid else None

    def on_node_death_actors(self, node_id: NodeID) -> List[ActorInfo]:
        """Actors that were living on a dead node (restart candidates)."""
        with self._lock:
            return [a for a in self.actors.values()
                    if a.node_id == node_id and a.state in (ALIVE, PENDING_CREATION)]

    # -- placement groups ---------------------------------------------------

    def register_placement_group(self, info: PlacementGroupInfo) -> None:
        with self._lock:
            self.placement_groups[info.pg_id] = info
        self._p(("pg", info))

    def set_pg_state(self, pg_id: PlacementGroupID, state: str) -> None:
        with self._lock:
            pg = self.placement_groups.get(pg_id)
            if pg:
                pg.state = state
        if pg:
            self._p(("pg", pg))
        self.publish("pg_state", (pg_id, state))

    def get_placement_group(self, pg_id: PlacementGroupID) -> Optional[PlacementGroupInfo]:
        with self._lock:
            return self.placement_groups.get(pg_id)

    # -- internal KV (reference: gcs_kv_manager.h) --------------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            ns = self._kv.setdefault(namespace, {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._kv_cond.notify_all()
        self._p(("kv_put", namespace, key, value))
        return True

    def kv_wait(self, key: str, namespace: str = "default",
                timeout: Optional[float] = None) -> Optional[bytes]:
        """Block until ``key`` exists (or timeout); returns its value.

        Event-driven replacement for client-side poll loops (collective
        rendezvous, p2p handshakes)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._kv_cond:
            while True:
                v = self._kv.get(namespace, {}).get(key)
                if v is not None:
                    return v
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._kv_cond.wait(remaining)

    def kv_get(self, key: str, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._kv.get(namespace, {}).get(key)

    def kv_del(self, key: str, namespace: str = "default") -> bool:
        with self._lock:
            existed = self._kv.get(namespace, {}).pop(key, None) is not None
        if existed:
            self._p(("kv_del", namespace, key))
        return existed

    def kv_keys(self, prefix: str = "", namespace: str = "default") -> List[str]:
        with self._lock:
            return [k for k in self._kv.get(namespace, {}) if k.startswith(prefix)]

    # -- pubsub (reference: src/ray/pubsub/publisher.h) ---------------------

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._lock:
            self._subscribers.setdefault(channel, []).append(callback)

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subscribers.get(channel, []))
        # Long-poll ring (reference: pubsub/publisher.h:356 — per-entity
        # buffered long-poll delivery): remote subscribers (workers,
        # clients, nodes) poll with their last-seen sequence number.
        with self._pubsub_cond:
            self._pubsub_seq += 1
            ring = self._pubsub_rings.setdefault(channel, [])
            ring.append((self._pubsub_seq, message))
            if len(ring) > self._pubsub_ring_cap:
                del ring[: len(ring) - self._pubsub_ring_cap]
            self._pubsub_cond.notify_all()
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass

    def pubsub_poll(self, channel: str, after_seq: int = 0,
                    timeout: Optional[float] = None):
        """Blocking long-poll: messages on ``channel`` with seq >
        after_seq, waking on publish (no client poll loop).  Returns
        (last_seq, [messages]); ([], after_seq) on timeout.  A subscriber
        that falls more than the ring size behind silently misses the
        overwritten messages (the reference's long-poll has the same
        bounded-buffer semantics)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._pubsub_cond:
            while True:
                ring = self._pubsub_rings.get(channel, [])
                fresh = [(s, m) for s, m in ring if s > after_seq]
                if fresh:
                    return fresh[-1][0], [m for _, m in fresh]
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    # Return the GLOBAL sequence head: no message on this
                    # channel can have a seq <= it that wasn't already in
                    # the ring (checked under this lock), so resuming from
                    # here never skips — and lets "subscribe from now"
                    # learn the head with a zero-timeout poll.
                    return self._pubsub_seq, []
                self._pubsub_cond.wait(remaining)
