"""Cluster scheduler: dependency resolution, policies, placement groups.

Maps the reference's two-level lease scheduler (reference:
src/ray/raylet/scheduling/cluster_lease_manager.h:41 queueing + node
selection, local_lease_manager.h:61 local dispatch, policies under
raylet/scheduling/policy/ — hybrid_scheduling_policy.cc pack-then-spread,
spread, node-affinity, bundle_scheduling_policy.cc) into one in-process
component: tasks enter a dependency stage (reference:
lease_dependency_manager.h), move to a ready queue, a policy picks a node,
resources are pinned, and the node's worker pool gets a dispatch callback.

TPU-first addition: resources are typed (``TPU`` chips, ``TPU-<gen>-head``
slice markers) and placement-group bundles model pod slices, so gang
placement of an SPMD worker group = one STRICT_SPREAD slice PG (the
SlicePlacementGroup concept, reference: python/ray/util/tpu.py:414, moved
into the scheduler proper).
"""

from __future__ import annotations

import random
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .config import Config
from .controller import (Controller, NodeInfo, PlacementGroupInfo, PG_CREATED,
                         PG_PENDING, PG_REMOVED)
from .ids import NodeID, ObjectID, PlacementGroupID, TaskID
from .protocol import TaskSpec
from .resources import ResourceSet
from ..util import telemetry
# Direct submodule import (not ``from .. import schedview``): the package
# attribute may not exist yet while ray_tpu/__init__ is mid-import.
from ..schedview import decisions as _dec

# Lifecycle stage names the scheduler reports through ``on_stage``
# (folded into the TaskEvent ring; see _private/events.py).
STAGE_READY = "READY"
STAGE_PLACED = "PLACED"

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: "NodeID"
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object  # PlacementGroup handle or PlacementGroupID
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class _PendingTask:
    spec: TaskSpec
    unresolved: Set[ObjectID]
    dispatch: Callable[[TaskSpec, NodeID], None]
    key: Any = None  # scheduling-class key (computed once at submit)
    attempts: int = 0  # failed placement rounds before this one


@dataclass
class _NodeState:
    info: NodeInfo
    available: ResourceSet
    # Per-PG-bundle reserved-and-still-free resources.
    bundle_available: Dict[Tuple[PlacementGroupID, int], ResourceSet] = field(
        default_factory=dict)


class Infeasible(Exception):
    """No alive node could ever satisfy the request."""


def _resource_gap(need: ResourceSet, avail: ResourceSet) -> Dict[str, float]:
    """Positive per-resource shortfalls of ``avail`` vs ``need`` (empty
    dict = fits)."""
    out: Dict[str, float] = {}
    for k, v in need.to_dict().items():
        short = v - avail.get(k)
        if short > 0:
            out[k] = round(short, 6)
    return out


def _gap_size(gap: Dict[str, float]) -> float:
    return sum(gap.values())


class ClusterScheduler:
    def __init__(self, controller: Controller,
                 object_ready: Callable[[ObjectID], bool]):
        self._controller = controller
        self._object_ready = object_ready
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, _NodeState] = {}
        # Ready tasks bucketed by scheduling class (reference: SchedulingKey
        # grouping in normal_task_submitter.h): each wake visits classes,
        # not tasks, so a full queue behind exhausted resources costs
        # O(classes) per pass instead of O(tasks).
        self._ready: "dict[Any, deque]" = {}
        self._ready_count = 0
        self._waiting: Dict[ObjectID, List[_PendingTask]] = defaultdict(list)
        self._infeasible: List[_PendingTask] = []
        # Draining nodes (preemption notice): unschedulable for NEW
        # leases/bundles; tasks already running there finish or evacuate.
        self._draining: Set[NodeID] = set()
        self._wake = threading.Condition(self._lock)
        self._running = True
        self._spread_rr = 0
        self._pending_pgs: List[PlacementGroupInfo] = []
        # Set by the Runtime: called with (spec, exc) when dispatch blows up.
        self.on_dispatch_error: Optional[Callable] = None
        # Set by the Runtime: called with (spec) when the cluster is full;
        # returns True if the task was queued ahead on a busy worker
        # (pipelined submission, reference: max_tasks_in_flight_per_worker
        # in the C++ submitter) — such tasks hold NO resource booking.
        self.try_pipeline: Optional[Callable] = None
        # -- control-plane telescope (ray_tpu.schedview) --------------------
        # Every placement decision lands in this bounded ring; explain()
        # reads queued tasks through _task_index.  Set by the Runtime:
        # on_stage(task_id_hex, stage) folds READY/PLACED lifecycle
        # stamps into the driver's TaskEvent ring.
        self.ring = _dec.DecisionRing(Config.get("sched_decision_ring_size"))
        self.on_stage: Optional[Callable[[str, str], None]] = None
        self._task_index: Dict[TaskID, _PendingTask] = {}
        self._pg_created_mono: Dict[PlacementGroupID, float] = {}
        # Metrics publisher state (rate-limited; hot paths only bump
        # plain ints/lists, the loop flushes into telemetry off-lock).
        # _publish_lock serializes the loop's periodic flush against a
        # ctl_sched_stats(force=True): the counts read-delta-write must
        # not double-inc the decisions counter.
        self._attempt_samples: List[int] = []
        self._published_counts: Dict[str, int] = {}
        self._publish_next_mono = 0.0
        self._publish_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, name="scheduler",
                                        daemon=True)
        self._thread.start()

    # -- node lifecycle -----------------------------------------------------

    def add_node(self, info: NodeInfo) -> None:
        with self._wake:
            self._nodes[info.node_id] = _NodeState(info, info.total_resources.copy())
            # Newly added capacity may unblock infeasible tasks.
            for t in self._infeasible:
                self._push_ready_locked(t)
            self._infeasible.clear()
            self._wake.notify_all()

    def remove_node(self, node_id: NodeID) -> None:
        with self._wake:
            self._nodes.pop(node_id, None)
            self._draining.discard(node_id)
            self._wake.notify_all()

    def set_draining(self, node_id: NodeID, draining: bool) -> None:
        """Fence a node off from new placements (drain notice), or lift
        the fence.  Existing bookings/bundles on the node are untouched —
        work already there drains through its own lifecycle."""
        with self._wake:
            if draining:
                self._draining.add(node_id)
            else:
                self._draining.discard(node_id)
                # Capacity became visible again: queued tasks may now fit.
                self._wake.notify_all()

    def available_resources(self) -> Dict[str, float]:
        """Schedulable capacity: draining nodes are excluded — their
        resources are about to vanish, and counting them would make
        elastic policies / the autoscaler size work onto a doomed host."""
        with self._lock:
            total = ResourceSet()
            for ns in self._nodes.values():
                if ns.info.node_id in self._draining:
                    continue
                total = total + ns.available
            return total.to_dict()

    def total_resources(self) -> Dict[str, float]:
        with self._lock:
            total = ResourceSet()
            for ns in self._nodes.values():
                total = total + ns.info.total_resources
            return total.to_dict()

    # -- task intake --------------------------------------------------------

    def submit(self, spec: TaskSpec,
               dispatch: Callable[[TaskSpec, NodeID], None]) -> None:
        deps = {a[1] for a in spec.arg_descs if a[0] == "ref"}
        deps |= {d[1] for d in spec.kwarg_descs.values() if d[0] == "ref"}
        # Readiness must be checked under the scheduler lock: an object can
        # become ready between the check and registration, and
        # notify_object_ready (which holds the same lock) would then have
        # already fired, stranding the task in _waiting forever.
        inline_node: Optional[NodeID] = None
        pipeline_ok = False
        trace = _dec.enabled()
        info: Optional[Dict[str, Any]] = {} if trace else None
        with self._wake:
            unresolved = {d for d in deps if not self._object_ready(d)}
            if not unresolved and not self._ready_count \
                    and not self._pending_pgs:
                # Submit-time fast path: with an empty queue, place and
                # book right here and dispatch on the caller's thread —
                # no scheduler-loop wakeup, no GIL handoff per task
                # (reference: normal_task_submitter.cc:142 pipelines
                # lease grants the same way).
                inline_node = self._try_place(spec, info)
                if inline_node is None and self.try_pipeline is not None \
                        and self._pipelineable(spec):
                    pipeline_ok = True  # attempt outside the lock
            if inline_node is None and not pipeline_ok:
                self._queue_task_locked(spec, dispatch, unresolved)
        if inline_node is not None:
            if trace:
                # Class payload is the RAW fields, not _sched_key: the
                # sorted-tuple build costs ~1.5us and this is the per-
                # submit fast path; _class_str normalizes at read time.
                self.ring.push(_dec.K_INLINE, spec.task_id.hex(), spec.name,
                               (spec.resources, spec.placement_group,
                                spec.bundle_index,
                                spec.scheduling_strategy),
                               info.get("candidates", 1),
                               info.get("rejected"), inline_node.hex(), 1)
                # No READY/PLACED stamps on the inline fast path: an
                # empty-queue placement has zero queue wait by
                # definition, and the extra record would tax every
                # submit to attribute a constant 0.  Queued tasks (the
                # loop path) carry the full stage breakdown.
            self._dispatch_safely(spec, dispatch, inline_node)
        elif pipeline_ok:
            if self.try_pipeline(spec):
                if trace:
                    self.ring.push(_dec.K_PIPELINE, spec.task_id.hex(),
                                   spec.name,
                                   (spec.resources, spec.placement_group,
                                    spec.bundle_index,
                                    spec.scheduling_strategy), 0,
                                   None, None, 1)
            else:
                with self._wake:
                    self._queue_task_locked(spec, dispatch, set())

    def take_pipelineable(self) -> Optional[_PendingTask]:
        """Pop a queued task eligible for pipelined dispatch (a pipelined
        completion freed a worker queue slot)."""
        with self._wake:
            if not self._running:
                return None
            for key in list(self._ready):
                bucket = self._ready[key]
                t = bucket[0]
                if self._pipelineable(t.spec):
                    bucket.popleft()
                    self._ready_count -= 1
                    if not bucket:
                        self._ready.pop(key, None)
                    self._task_index.pop(t.spec.task_id, None)
                    if _dec.enabled():
                        self.ring.push(_dec.K_PIPELINE, t.spec.task_id.hex(),
                                       t.spec.name, t.key, 0, None, None,
                                       t.attempts + 1)
                    return t
            return None

    @staticmethod
    def _pipelineable(spec: TaskSpec) -> bool:
        """Plain CPU-only tasks can queue ahead on a busy worker: execution
        stays serial per worker, so actual parallelism remains bounded by
        the booked capacity."""
        return (spec.placement_group is None
                and spec.scheduling_strategy is None
                and spec.runtime_env is None
                and spec.actor_id is None and spec.create_actor_id is None
                and all(k == "CPU" for k in spec.resources.keys()))

    def _queue_task_locked(self, spec: TaskSpec, dispatch,
                           unresolved: Set[ObjectID]) -> None:
        task = _PendingTask(spec, unresolved, dispatch,
                            self._sched_key(spec))
        self._task_index[spec.task_id] = task
        if unresolved:
            for d in unresolved:
                self._waiting[d].append(task)
        else:
            self._push_ready_locked(task)
            # Wake the loop only when the task has a chance of placing
            # right now: with every worker busy, the wakeup is a pure GIL
            # handoff per submit (measured ~100us each at 2k submits/s)
            # and release() will wake the loop anyway when capacity frees.
            # Both paths hold this lock, so the check-then-notify cannot
            # miss a concurrent release.
            if self._capacity_hint(spec):
                self._wake.notify_all()

    def _dispatch_safely(self, spec: TaskSpec, dispatch, node_id: NodeID):
        try:
            dispatch(spec, node_id)
        except Exception as exc:
            # Undo the resource deduction and surface the error; silently
            # dropping would leak capacity and hang get().
            self.release(node_id, spec.resources, spec.placement_group,
                         spec.bundle_index)
            if self.on_dispatch_error is not None:
                try:
                    self.on_dispatch_error(spec, exc)
                except Exception as e:
                    telemetry.note_swallowed("scheduler.on_dispatch_error", e)

    def exchange_finished(self, node_id: NodeID,
                          spec: TaskSpec) -> Optional[_PendingTask]:
        """A task of ``spec``'s scheduling class just finished on
        ``node_id``: transfer its resource booking to a queued task of the
        SAME class and return it for immediate dispatch (lease reuse,
        reference: normal-task lease pipelining) — or release the booking
        and return None.  Caller restricts this to plain tasks (no PG, no
        TPU grant, no runtime_env)."""
        key = self._sched_key(spec)
        with self._wake:
            # Reuse only while this class is the ONLY queued class and the
            # scheduler is live: with other classes waiting, release and
            # let the loop's FIFO-over-classes scan arbitrate — an endless
            # same-class stream must not starve earlier-queued classes.
            bucket = self._ready.get(key)
            if bucket and self._running and len(self._ready) == 1 \
                    and not self._pending_pgs:
                task = bucket.popleft()
                self._ready_count -= 1
                if not bucket:
                    self._ready.pop(key, None)
                self._task_index.pop(task.spec.task_id, None)
                if _dec.enabled():
                    # Ring record only — like the inline path, lease
                    # reuse is a fast path (placed the instant a
                    # sibling finished) and skips the PLACED lifecycle
                    # stamp; the loop path keeps full stage stamps.
                    self.ring.push(_dec.K_EXCHANGE, task.spec.task_id.hex(),
                                   task.spec.name, key, 1, None,
                                   node_id.hex(), task.attempts + 1)
                return task
        self.release(node_id, spec.resources)
        return None

    def _capacity_hint(self, spec: TaskSpec) -> bool:
        """Cheap may-fit check (false negatives are latency-free thanks to
        release()'s notify; when unsure, say yes)."""
        need = spec.resources
        if spec.placement_group is not None:
            return True
        for ns in self._nodes.values():
            if ns.info.node_id not in self._draining and \
                    need.fits(ns.available):
                return True
        return False

    def _push_ready_locked(self, task: _PendingTask) -> None:
        if task.key is None:
            task.key = self._sched_key(task.spec)
        self._ready.setdefault(task.key, deque()).append(task)
        self._ready_count += 1

    def notify_object_ready(self, object_id: ObjectID) -> None:
        trace = self.on_stage is not None and _dec.enabled()
        stamped: List[str] = []
        with self._wake:
            tasks = self._waiting.pop(object_id, [])
            moved = False
            for t in tasks:
                t.unresolved.discard(object_id)
                if not t.unresolved:
                    self._push_ready_locked(t)
                    moved = True
                    if trace:
                        # READY marks DEPS RESOLVED — only tasks that
                        # actually waited on objects get the stamp; a
                        # dep-free task's queue wait is PLACED-submit
                        # and an extra zero-length stage would tax
                        # every queued submit to record it.
                        stamped.append(t.spec.task_id.hex())
            if moved:
                self._wake.notify_all()
        # Stage stamps ride OUTSIDE the condvar (RT404): on_stage fans
        # into the decision ring / user tracing, and a slow consumer
        # there must not convoy submitters and the scheduler loop.
        for tid in stamped:
            self.on_stage(tid, STAGE_READY)

    def release(self, node_id: NodeID, resources: ResourceSet,
                pg: Optional[PlacementGroupID] = None,
                bundle_index: int = -1) -> None:
        with self._wake:
            ns = self._nodes.get(node_id)
            if ns is None:
                return
            if pg is not None:
                key = (pg, bundle_index) if bundle_index >= 0 else None
                if key is not None and key in ns.bundle_available:
                    ns.bundle_available[key] = ns.bundle_available[key] + resources
                else:
                    # PG was removed while the task ran: resources go back to
                    # the node's main pool.
                    ns.available = ns.available + resources
            else:
                ns.available = ns.available + resources
            self._wake.notify_all()

    # -- scheduling loop ----------------------------------------------------

    @staticmethod
    def _sched_key(spec: TaskSpec):
        """Scheduling-class key (reference: SchedulingKey in
        normal_task_submitter.h): tasks with identical resource shape,
        placement target and strategy place identically, so one failed
        placement disqualifies the whole class for this round — turning the
        O(queue) rescan per wake into O(distinct classes)."""
        res = tuple(sorted(spec.resources.to_dict().items()))
        strat = spec.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            strat = ("affinity", strat.node_id, strat.soft)
        return (res, spec.placement_group, spec.bundle_index, strat)

    def _loop(self) -> None:
        info: Dict[str, Any] = {}  # reused per placement attempt
        while True:
            # Phase 1 (locked): pick placements and deduct resources.
            # Phase 2 (unlocked): run the dispatches — arg resolution,
            # spec pickling and the worker-pipe send are the expensive
            # part, and holding the condvar through them would serialize
            # every submit/release/notify in the system behind each
            # dispatch (measured: ~770us average lock wait in the async
            # task microbenchmark before this split).
            to_dispatch = []
            with self._wake:
                while self._running and not self._ready_count:
                    self._retry_pending_pgs_locked()
                    self._wake.wait(timeout=0.5)
                if not self._running:
                    return
                self._retry_pending_pgs_locked()
                trace = _dec.enabled()
                for key in list(self._ready):
                    bucket = self._ready.get(key)
                    while bucket:
                        task = bucket[0]
                        info.clear()
                        node_id = self._try_place(task.spec, info)
                        if node_id is None:
                            task.attempts += 1
                            if trace:
                                self.ring.push(
                                    _dec.K_REJECT, task.spec.task_id.hex(),
                                    task.spec.name, key,
                                    info.get("candidates", 0),
                                    dict(info.get("rejected") or {}),
                                    None, task.attempts)
                            if info.get("infeasible"):
                                # Park the whole class: no node's TOTAL
                                # resources could ever satisfy it, so
                                # rescanning it every wake is pure
                                # overhead.  add_node revives parked
                                # tasks (new capacity may fit them).
                                if trace:
                                    self.ring.push(
                                        _dec.K_INFEASIBLE,
                                        task.spec.task_id.hex(),
                                        task.spec.name, key, 0,
                                        {_dec.R_INFEASIBLE:
                                         max(1, len(self._nodes))},
                                        None, task.attempts)
                                self._infeasible.extend(bucket)
                                self._ready_count -= len(bucket)
                                bucket.clear()
                            break  # whole class blocked this round
                        task.attempts += 1
                        bucket.popleft()
                        self._ready_count -= 1
                        self._task_index.pop(task.spec.task_id, None)
                        to_dispatch.append((task, node_id,
                                            info.get("candidates", 1)))
                    if not bucket:
                        self._ready.pop(key, None)
                if self._ready_count and not to_dispatch:
                    # Nothing placeable right now; sleep until resources
                    # free (release/notify wake us).
                    self._wake.wait(timeout=0.05)
            # Decision records for the placed batch land OUTSIDE the
            # condvar (every submit/release/notify serializes behind it)
            # and BEFORE the dispatches, so a synchronously-completing
            # dispatch can never file its SUBMITTED/RUNNING transitions
            # ahead of our PLACED stamp.
            if trace and to_dispatch:
                for task, node_id, cands in to_dispatch:
                    tid_hex = task.spec.task_id.hex()
                    self.ring.push(_dec.K_LOOP, tid_hex, task.spec.name,
                                   task.key, cands, None, node_id.hex(),
                                   task.attempts)
                    if self.on_stage is not None:
                        self.on_stage(tid_hex, STAGE_PLACED)
                with self._lock:
                    if len(self._attempt_samples) < 512:
                        self._attempt_samples.extend(
                            t.attempts for t, _n, _c in to_dispatch)
            for task, node_id, _cands in to_dispatch:
                self._dispatch_safely(task.spec, task.dispatch, node_id)
            self._maybe_publish_metrics()

    def stop(self) -> None:
        with self._wake:
            self._running = False
            self._wake.notify_all()
        # Join (bounded) so standalone schedulers — the control_plane
        # bench harness, unit tests — never leak their loop thread into
        # the sanitizer's shutdown diff.  Dispatches run ON the loop
        # thread, so a stop() from a dispatch callback must not join.
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=2.0)

    # -- placement ----------------------------------------------------------

    def _bundle_key(self, ns: _NodeState, pg: PlacementGroupID,
                    bundle_index: int, need: ResourceSet):
        if bundle_index >= 0:
            key = (pg, bundle_index)
            return key if key in ns.bundle_available else None
        # Wildcard bundle: first bundle on this node with room.
        for key, avail in ns.bundle_available.items():
            if key[0] == pg and need.fits(avail):
                return key
        return None

    def _try_place(self, spec: TaskSpec,
                   info: Optional[Dict[str, Any]] = None
                   ) -> Optional[NodeID]:
        """Pick + book a node for ``spec`` (None = blocked this round).

        ``info``, when given, receives the decision record the schedview
        ring keeps: candidate count, per-reason rejection tallies, the
        policy that picked, and an ``infeasible`` flag when no node's
        TOTAL resources could ever satisfy the request.  Success paths
        fill only ``candidates``/``policy`` (O(1) extra); the tally pass
        runs only on failure, which is off the placement hot path."""
        need = spec.resources
        if spec.placement_group is not None:
            for ns in self._nodes.values():
                key = self._bundle_key(ns, spec.placement_group,
                                       spec.bundle_index, need)
                if key is not None and need.fits(ns.bundle_available[key]):
                    ns.bundle_available[key] = ns.bundle_available[key] - need
                    if info is not None:
                        info["candidates"] = 1
                        info["policy"] = "pg_bundle"
                    return ns.info.node_id
            if info is not None:
                info["candidates"] = 0
                info["rejected"] = {_dec.R_BUNDLE: max(1, len(self._nodes))}
            return None

        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            ns = self._nodes.get(strategy.node_id)
            if ns is not None and need.fits(ns.available) and \
                    strategy.node_id not in self._draining:
                ns.available = ns.available - need
                if info is not None:
                    info["candidates"] = 1
                    info["policy"] = "affinity"
                return ns.info.node_id
            if not strategy.soft:
                if info is not None:
                    if ns is None:
                        why = _dec.R_AFFINITY
                    elif strategy.node_id in self._draining:
                        why = _dec.R_DRAINING
                    else:
                        why = _dec.R_INSUFFICIENT
                    info["candidates"] = 0
                    info["rejected"] = {_dec.R_AFFINITY: 1} \
                        if why == _dec.R_AFFINITY else {why: 1,
                                                        _dec.R_AFFINITY: 1}
                return None  # stays queued until that node frees up

        candidates = [ns for ns in self._nodes.values()
                      if ns.info.node_id not in self._draining
                      and need.fits(ns.available)]
        if info is not None:
            info["candidates"] = len(candidates)
        if not candidates:
            if info is not None:
                rejected: Dict[str, int] = {}
                draining_n = insufficient = 0
                for ns in self._nodes.values():
                    if ns.info.node_id in self._draining:
                        draining_n += 1
                    else:
                        insufficient += 1
                if not self._nodes:
                    rejected[_dec.R_NO_NODES] = 1
                if draining_n:
                    rejected[_dec.R_DRAINING] = draining_n
                if insufficient:
                    rejected[_dec.R_INSUFFICIENT] = insufficient
                if not any(need.fits(ns.info.total_resources)
                           for ns in self._nodes.values()):
                    # No alive node could EVER satisfy this shape.
                    info["infeasible"] = True
                    rejected[_dec.R_INFEASIBLE] = max(1, len(self._nodes))
                info["rejected"] = rejected
            return None

        if strategy == "SPREAD":
            self._spread_rr += 1
            ns = candidates[self._spread_rr % len(candidates)]
            if info is not None:
                info["policy"] = "spread"
        else:
            ns = self._hybrid_pick(candidates)
            if info is not None:
                info["policy"] = "hybrid"
        ns.available = ns.available - need
        return ns.info.node_id

    def _hybrid_pick(self, candidates: List[_NodeState]) -> _NodeState:
        """Pack onto busiest node under the threshold, else least utilized
        (reference: hybrid_scheduling_policy.cc)."""
        thresh = Config.get("scheduler_spread_threshold")

        def utilization(ns: _NodeState) -> float:
            utils = []
            for k, total in ns.info.total_resources.items():
                if total > 0:
                    utils.append(1.0 - ns.available.get(k) / total)
            return max(utils) if utils else 0.0

        under = [ns for ns in candidates if utilization(ns) < thresh]
        if under:
            return max(under, key=utilization)
        return min(candidates, key=utilization)

    # -- placement groups ---------------------------------------------------

    def create_placement_group(self, pg: PlacementGroupInfo) -> bool:
        """Two-phase reserve: compute full assignment against a snapshot,
        commit only if every bundle fits (reference:
        gcs_placement_group_scheduler.h:115 prepare/commit).  A group that
        does not fit yet stays PENDING and is retried whenever capacity
        frees up (reference: GcsPlacementGroupManager pending queue)."""
        with self._wake:
            self._pg_created_mono.setdefault(pg.pg_id, time.monotonic())
            if self._try_commit_pg(pg):
                return True
            if _dec.enabled():
                self.ring.push(
                    _dec.K_PG_REJECT, pg.pg_id.hex(),
                    pg.name or "placement_group", pg.strategy, 0,
                    {_dec.R_BUNDLE:
                     sum(1 for b in pg.bundles if b.node_id is None)},
                    None, 1)
            self._pending_pgs.append(pg)
            return False

    def _try_commit_pg(self, pg: PlacementGroupInfo) -> bool:
        """Commit every still-unplaced bundle (all of them on first create;
        just the lost ones after a node death re-plan)."""
        pending = [b for b in pg.bundles if b.node_id is None]
        if not pending:
            self._controller.set_pg_state(pg.pg_id, PG_CREATED)
            self._note_pg_committed(pg, [])
            return True
        # Draining nodes never receive NEW bundles (existing bundles on a
        # draining node stay committed; evacuation is the owner's call).
        snapshot = {nid: ns.available.copy()
                    for nid, ns in self._nodes.items()
                    if nid not in self._draining}
        used = {b.node_id for b in pg.bundles if b.node_id is not None}
        assignment = self._plan_bundles(pg, snapshot, pending, used)
        if assignment is None:
            return False
        for bundle, node_id in zip(pending, assignment):
            ns = self._nodes[node_id]
            ns.available = ns.available - bundle.resources
            ns.bundle_available[(pg.pg_id, bundle.index)] = bundle.resources.copy()
            bundle.node_id = node_id
        self._controller.set_pg_state(pg.pg_id, PG_CREATED)
        self._note_pg_committed(pg, assignment)
        self._wake.notify_all()
        return True

    def _note_pg_committed(self, pg: PlacementGroupInfo,
                           assignment: List[NodeID]) -> None:
        """Book the two-phase-commit latency + decision record for a PG
        that just reached CREATED (PG creates are rare — direct
        telemetry is fine here, unlike the per-task path)."""
        created = self._pg_created_mono.pop(pg.pg_id, None)
        if created is not None:
            # PG commits are rare (not the per-task hot path), so one
            # observe under the lock is cheaper than restructuring the
            # two-phase-commit flow to stamp outside it.
            telemetry.observe("ray_tpu_sched_pg_commit_seconds",  # ray-tpu: noqa[RT404]
                              max(0.0, time.monotonic() - created))
        if _dec.enabled():
            nodes = {b.node_id.hex()[:12] for b in pg.bundles
                     if b.node_id is not None}
            self.ring.push(_dec.K_PG_COMMIT, pg.pg_id.hex(),
                           pg.name or "placement_group", pg.strategy,
                           len(nodes), None,
                           ",".join(sorted(nodes)) or None, 1)

    def reschedule_lost_bundles(self, pg: PlacementGroupInfo,
                                dead_node: NodeID) -> None:
        """Re-plan the bundles a dead node took with it; live bundles keep
        their placement (reference: GcsPlacementGroupManager rescheduling on
        node death)."""
        with self._wake:
            if pg.state == PG_REMOVED:
                return
            lost = False
            for b in pg.bundles:
                if b.node_id == dead_node:
                    b.node_id = None
                    lost = True
            if not lost:
                return
            self._controller.set_pg_state(pg.pg_id, PG_PENDING)
            # Re-stamp: the commit-latency histogram books the re-plan
            # window (node death -> bundles recommitted) as its own
            # two-phase commit.
            self._pg_created_mono.setdefault(pg.pg_id, time.monotonic())
            if not self._try_commit_pg(pg) and pg not in self._pending_pgs:
                self._pending_pgs.append(pg)

    def _retry_pending_pgs_locked(self) -> None:
        if not self._pending_pgs:
            return
        still_pending = []
        for pg in self._pending_pgs:
            if pg.state == PG_REMOVED:
                continue
            if not self._try_commit_pg(pg):
                still_pending.append(pg)
        self._pending_pgs = still_pending

    def _plan_bundles(self, pg: PlacementGroupInfo,
                      snapshot: Dict[NodeID, ResourceSet],
                      bundles=None,
                      used_nodes: Optional[Set[NodeID]] = None
                      ) -> Optional[List[NodeID]]:
        bundles = pg.bundles if bundles is None else bundles
        node_ids = list(snapshot.keys())
        if not node_ids:
            return None
        assignment: List[NodeID] = []
        if pg.strategy == STRICT_PACK:
            # All bundles (incl. survivors) must share one node; a partial
            # re-plan must land on the surviving bundles' node if any.
            anchor = {b.node_id for b in pg.bundles if b.node_id is not None}
            cands = list(anchor) if anchor else node_ids
            for nid in cands:
                if nid not in snapshot:
                    continue
                avail = snapshot[nid].copy()
                ok = True
                for b in bundles:
                    if not b.resources.fits(avail):
                        ok = False
                        break
                    avail = avail - b.resources
                if ok:
                    return [nid] * len(bundles)
            return None
        used_nodes = set(used_nodes or ())
        order = node_ids if pg.strategy != SPREAD else random.sample(
            node_ids, len(node_ids))
        for b in bundles:
            placed = None
            if pg.strategy == STRICT_SPREAD:
                cands = [n for n in order if n not in used_nodes
                         and b.resources.fits(snapshot[n])]
            elif pg.strategy == SPREAD:
                cands = sorted(
                    (n for n in order if b.resources.fits(snapshot[n])),
                    key=lambda n: n in used_nodes)
            else:  # PACK: prefer already-used nodes
                cands = sorted(
                    (n for n in order if b.resources.fits(snapshot[n])),
                    key=lambda n: n not in used_nodes)
            if cands:
                placed = cands[0]
            if placed is None:
                return None
            snapshot[placed] = snapshot[placed] - b.resources
            used_nodes.add(placed)
            assignment.append(placed)
        return assignment

    def remove_placement_group(self, pg: PlacementGroupInfo) -> None:
        with self._wake:
            for b in pg.bundles:
                if b.node_id is None:
                    continue
                ns = self._nodes.get(b.node_id)
                if ns is None:
                    continue
                remaining = ns.bundle_available.pop((pg.pg_id, b.index), None)
                if remaining is not None:
                    # Return the whole bundle; in-use slices return via release().
                    ns.available = ns.available + remaining
                b.node_id = None
            self._pg_created_mono.pop(pg.pg_id, None)
            self._controller.set_pg_state(pg.pg_id, PG_REMOVED)
            self._wake.notify_all()

    def num_pending(self) -> int:
        with self._lock:
            return self._ready_count + len(self._infeasible) + sum(
                len(v) for v in self._waiting.values())

    # -- control-plane telescope (schedview) --------------------------------

    def pending_task_ids(self) -> List[TaskID]:
        """Every task the scheduler currently holds (waiting on deps,
        ready, or parked infeasible)."""
        with self._lock:
            return list(self._task_index)

    def queue_depths(self) -> Dict[str, int]:
        """Live queue depths by stage (the `ray-tpu sched` gauges)."""
        with self._lock:
            return {
                "ready": self._ready_count,
                "ready_classes": len(self._ready),
                "waiting_deps": sum(len(v)
                                    for v in self._waiting.values()),
                "infeasible": len(self._infeasible),
                "pending_pgs": len(self._pending_pgs),
            }

    def _maybe_publish_metrics(self, force: bool = False) -> None:
        """Rate-limited flush of queue depths / decision counts into the
        telemetry catalog (the hot paths only bump plain ints; this runs
        on the scheduler loop OUTSIDE the condvar, ~1/s).  A concurrent
        publisher (loop tick vs ctl_sched_stats poll) skips instead of
        double-counting the counter deltas."""
        if not self._publish_lock.acquire(blocking=False):
            return
        try:
            now = time.monotonic()
            if not force and now < self._publish_next_mono:
                return
            self._publish_next_mono = now + 1.0
            with self._lock:
                depths = {
                    "ready": self._ready_count,
                    "waiting_deps": sum(len(v)
                                        for v in self._waiting.values()),
                    "infeasible": len(self._infeasible),
                    "pending_pgs": len(self._pending_pgs),
                }
                samples, self._attempt_samples = self._attempt_samples, []
            # _publish_lock exists ONLY to serialize these publishes (it
            # single-admits publishers; schedulers never block on it) —
            # publishing under it is the lock's whole purpose, and the
            # hot scheduler lock was already dropped above.
            for queue, depth in depths.items():
                telemetry.set_gauge("ray_tpu_sched_queue_depth",  # ray-tpu: noqa[RT404]
                                    float(depth), tags={"queue": queue})
            counts = dict(self.ring.counts)
            for kind, total in counts.items():
                delta = total - self._published_counts.get(kind, 0)
                if delta > 0:
                    telemetry.inc("ray_tpu_sched_decisions_total",  # ray-tpu: noqa[RT404]
                                  float(delta), tags={"kind": kind})
            self._published_counts = counts
            telemetry.observe_many("ray_tpu_sched_placement_attempts",  # ray-tpu: noqa[RT404]
                                   [float(a) for a in samples])
        finally:
            self._publish_lock.release()

    def explain_task(self, task_id: TaskID) -> Optional[Dict[str, Any]]:
        """Why is this task still pending?  None if the scheduler does
        not hold it (it was placed, finished, or never queued — the
        caller falls back to the decision ring / task events).

        The analysis is a DRY placement run against live state: it never
        books resources, and it names the closest-fit node plus the
        exact resource gap when nothing fits."""
        with self._lock:
            t = self._task_index.get(task_id)
            if t is None:
                return None
            if t.unresolved:
                return {
                    "status": "pending_deps",
                    "reasons": [_dec.R_PENDING_DEPS],
                    "unresolved_deps": sorted(d.hex()
                                              for d in t.unresolved),
                    "attempts": t.attempts,
                }
            out = self._analyze_locked(t.spec)
            out["attempts"] = t.attempts
            return out

    def _analyze_locked(self, spec: TaskSpec) -> Dict[str, Any]:
        """Non-mutating placement analysis for a ready-but-unplaced
        task: reason codes, candidate count, closest-fit node + gap."""
        need = spec.resources
        info: Dict[str, Any] = {}
        out: Dict[str, Any] = {"status": "queued"}
        if spec.placement_group is not None:
            committed = [
                key for ns in self._nodes.values()
                for key in ns.bundle_available
                if key[0] == spec.placement_group
            ]
            out["reasons"] = [_dec.R_BUNDLE]
            out["pg"] = {
                "placement_group_id": spec.placement_group.hex(),
                "bundle_index": spec.bundle_index,
                "committed_bundles": sorted(k[1] for k in committed),
            }
            # A committed-but-full bundle is a capacity gap, not a
            # missing commit: report the gap of the closest bundle.
            best_gap = None
            for ns in self._nodes.values():
                for key, avail in ns.bundle_available.items():
                    if key[0] != spec.placement_group:
                        continue
                    if spec.bundle_index >= 0 and \
                            key[1] != spec.bundle_index:
                        continue
                    gap = _resource_gap(need, avail)
                    if best_gap is None or \
                            _gap_size(gap) < _gap_size(best_gap[1]):
                        best_gap = (ns.info.node_id.hex(), gap)
            if best_gap is not None:
                out["closest_fit"] = {"node_id": best_gap[0],
                                      "gap": best_gap[1]}
            return out

        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy) \
                and not strategy.soft:
            ns = self._nodes.get(strategy.node_id)
            reasons = [_dec.R_AFFINITY]
            if ns is not None:
                if strategy.node_id in self._draining:
                    reasons.append(_dec.R_DRAINING)
                elif not need.fits(ns.available):
                    reasons.append(_dec.R_INSUFFICIENT)
                    out["closest_fit"] = {
                        "node_id": strategy.node_id.hex(),
                        "gap": _resource_gap(need, ns.available) or {}}
            out["reasons"] = reasons
            out["affinity_node"] = strategy.node_id.hex()
            return out

        # Normal strategy: reuse _try_place's failure tallies (dry: an
        # analysis pass must never book, and candidates>0 here only
        # means the scheduler loop has not reached the task yet).
        saved = [(ns, ns.available) for ns in self._nodes.values()]
        node = self._try_place(spec, info)
        if node is not None:
            # Roll the dry booking back.
            for ns, avail in saved:
                ns.available = avail
            out["reasons"] = []
            out["status"] = "placeable"
            out["candidates"] = info.get("candidates", 1)
            return out
        rejected = info.get("rejected") or {}
        out["rejected"] = rejected
        out["candidates"] = info.get("candidates", 0)
        out["reasons"] = sorted(rejected,
                                key=lambda r: -rejected[r]) or \
            [_dec.R_INSUFFICIENT]
        if info.get("infeasible"):
            out["status"] = "infeasible"
        # Closest fit: the non-draining node with the smallest total
        # resource gap (what the autoscaler would need to add).
        best = None
        for ns in self._nodes.values():
            if ns.info.node_id in self._draining:
                continue
            gap = _resource_gap(need, ns.available)
            if best is None or _gap_size(gap) < _gap_size(best[1]):
                best = (ns.info.node_id.hex(), gap)
        if best is not None:
            out["closest_fit"] = {"node_id": best[0], "gap": best[1]}
        return out

    def pending_demand(self, include_pg_bundles: bool = True
                       ) -> List[Dict[str, float]]:
        """Unplaced resource shapes (one entry per queued task) — the
        autoscaler's demand feed (reference: GcsAutoscalerStateManager
        resource demand -> v2/scheduler.py bin-packing).

        ``include_pg_bundles=False`` leaves pending placement-group
        bundles out — gang-aware consumers take them atomically through
        ``pending_gang_demand`` instead."""
        with self._lock:
            out: List[Dict[str, float]] = []
            for bucket in self._ready.values():
                for t in bucket:
                    out.append(t.spec.resources.to_dict())
            for t in self._infeasible:
                out.append(t.spec.resources.to_dict())
            if not include_pg_bundles:
                return out
            pending_pg_shapes = []
            for pg in self._pending_pgs:
                for b in pg.bundles:
                    if b.node_id is None:
                        pending_pg_shapes.append(b.resources.to_dict())
            return out + pending_pg_shapes

    def pending_gang_demand(self) -> List[Tuple[str, List[Dict[str, float]],
                                                List]]:
        """Pending placement groups as atomic gangs: (strategy, [unplaced
        bundle shapes], [node_ids already holding this PG's bundles]) per
        pending PG.  A TPU slice reservation (SlicePlacementGroup ->
        STRICT_SPREAD PG) is exactly such a gang: the autoscaler must
        launch the whole multi-host node group or nothing, and spread
        bundles can never land on nodes the PG already occupies
        (reference: v2/scheduler.py:822 gang requests)."""
        with self._lock:
            out = []
            for pg in self._pending_pgs:
                shapes = [b.resources.to_dict() for b in pg.bundles
                          if b.node_id is None]
                placed = [b.node_id for b in pg.bundles
                          if b.node_id is not None]
                if shapes:
                    out.append((pg.strategy, shapes, placed))
            return out

    def per_node_available(self) -> Dict[NodeID, Dict[str, float]]:
        """Free resources per node (gang placement feasibility checks).
        Draining nodes are excluded — the drain fence and the
        autoscaler's gang launcher must agree: a doomed node's free
        capacity must never let a pending gang look placeable (the
        commit path would refuse it and the gang would wedge), nor
        suppress the whole-slice replacement buy."""
        with self._lock:
            return {nid: ns.available.to_dict()
                    for nid, ns in self._nodes.items()
                    if nid not in self._draining}
