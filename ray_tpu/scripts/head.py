"""Head process: runtime + job server, launched by ``ray-tpu start --head``.

Reference: ``ray start --head`` (python/ray/scripts/scripts.py:799) which
boots GCS + raylet + dashboard; here one process hosts the driver runtime,
the JobManager and its REST server, and stays up until SIGTERM.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=8265)
    p.add_argument("--node-port", type=int, default=6380,
                   help="TCP join port for cluster nodes (0 = ephemeral)")
    p.add_argument("--token", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--address-file", default="/tmp/ray_tpu/head_address")
    p.add_argument("--dashboard-port", type=int, default=8266,
                   help="dashboard HTTP port (0 = ephemeral, -1 = off)")
    p.add_argument("--state-dir", default="/tmp/ray_tpu/head_state",
                   help="Durable controller-state dir (WAL + snapshot); a "
                        "restarted head replays it — actors restart from "
                        "their creation specs, PGs re-plan, KV survives. "
                        "Empty string disables persistence.")
    args = p.parse_args(argv)

    import ray_tpu
    from ray_tpu.job_submission import JobManager
    from ray_tpu.job_submission.server import JobServer

    token_str = args.token or os.urandom(16).hex()
    rt = ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                      head_port=args.node_port,
                      cluster_token=token_str.encode(),
                      state_dir=args.state_dir or None)
    manager = JobManager()
    server = JobServer(manager, port=args.port)
    dashboard = None
    if args.dashboard_port >= 0:
        try:
            from ray_tpu.dashboard import start_dashboard
            dashboard = start_dashboard(port=args.dashboard_port)
            print(f"dashboard at http://127.0.0.1:{dashboard.port}",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"dashboard failed to start: {e!r}", flush=True)

    node_addr = "%s:%d" % rt.head_server.address
    os.makedirs(os.path.dirname(args.address_file), exist_ok=True)
    # The cluster token is a secret (the join port unpickles peer messages);
    # persist it 0600 so local joiners can read it, remote ones get it from
    # the operator.
    fd = os.open(args.address_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                 0o600)
    # O_CREAT's mode only applies to new files; a pre-existing address file
    # must also be clamped before the token lands in it.
    os.fchmod(fd, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump({"address": server.address, "pid": os.getpid(),
                   "node_address": node_addr, "token": token_str}, f)

    stop = {"flag": False}

    def on_term(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    print(f"ray_tpu head listening on {server.address}", flush=True)
    while not stop["flag"]:
        time.sleep(0.2)
    server.stop()
    if dashboard is not None:
        dashboard.stop()
    ray_tpu.shutdown()
    try:
        os.unlink(args.address_file)
    except FileNotFoundError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
