"""Mesh runtime tests: MeshConfig validation/factorization, elastic
sizing snapped to mesh-tileable worlds (the drain-to-invalid-size fix),
the mesh-reshape restore matrix at the checkpoint-format level, and a
2-worker trainer e2e under ``xla_force_host_platform_device_count`` that
saves on one mesh shape and restores — bit-exactly — onto another, each
process reading only the index slices its devices own."""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from ray_tpu.train import MeshConfig
from ray_tpu.train.mesh import reshape as R


class TestMeshConfig:
    def test_parse(self):
        mc = MeshConfig.parse("dp2xfsdp4")
        assert (mc.dp, mc.fsdp) == (2, 4)
        assert MeshConfig.parse("auto").auto
        mc = MeshConfig.parse("pp2xfsdp4")
        assert (mc.pp, mc.fsdp) == (2, 4)

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            MeshConfig.parse("dp2xbogus4")
        with pytest.raises(ValueError):
            MeshConfig.parse("dp2xdp4")  # repeated axis

    def test_spec_resolution_and_absorb(self):
        spec = MeshConfig.parse("dp2xfsdp4").spec_for(8)
        assert (spec.dp, spec.fsdp) == (2, 4)
        spec = MeshConfig(dp=-1, fsdp=2).spec_for(6)
        assert (spec.dp, spec.fsdp) == (3, 2)
        with pytest.raises(ValueError):
            MeshConfig.parse("dp2xfsdp4").spec_for(6)

    def test_auto_factorization(self):
        # fsdp = largest divisor <= 8 (one host's ICI domain), dp rest.
        spec = MeshConfig(auto=True).spec_for(8)
        assert (spec.dp, spec.fsdp) == (1, 8)
        spec = MeshConfig(auto=True).spec_for(16)
        assert (spec.dp, spec.fsdp) == (2, 8)
        spec = MeshConfig(auto=True).spec_for(12)
        assert (spec.dp, spec.fsdp) == (2, 6)
        # Multi-slice: dp must stay divisible by num_slices.
        spec = MeshConfig(auto=True).spec_for(16, num_slices=2)
        assert spec.dp % 2 == 0

    def test_valid_and_nearest_world(self):
        mc = MeshConfig(dp=-1, fsdp=2)
        assert [w for w in range(1, 9) if mc.valid_world(w)] == [2, 4, 6, 8]
        # The drain-to-invalid-size case: 3 survivors snap DOWN to 2.
        assert mc.nearest_valid_world(3) == 2
        # Nothing valid below: snap UP within the ceiling.
        assert mc.nearest_valid_world(1, ceiling=4) == 2
        assert mc.nearest_valid_world(1) is None

    def test_devices_per_worker_scales_tiling(self):
        mc = MeshConfig(fsdp=8, devices_per_worker=4)
        assert mc.valid_world(2)        # 2 workers x 4 devices = fsdp8
        assert not mc.valid_world(3)

    def test_validate_scaling_fails_fast(self):
        from ray_tpu.train import ScalingConfig
        mc = MeshConfig(fsdp=8)
        with pytest.raises(ValueError):
            mc.validate_scaling(ScalingConfig(num_workers=6))
        # Elastic range containing no tileable world.
        with pytest.raises(ValueError):
            MeshConfig(fsdp=8).validate_scaling(
                ScalingConfig(min_workers=2, max_workers=5))
        # A tileable size inside the range passes.
        MeshConfig(fsdp=4).validate_scaling(
            ScalingConfig(min_workers=2, max_workers=5))

    def test_rules_overrides(self):
        rules = MeshConfig(tp=4, rules={"embed": "tp",
                                        "heads": None}).sharding_rules()
        assert rules.axes_for("embed") == "tp"
        assert rules.axes_for("heads") is None
        assert rules.axes_for("mlp") == "tp"  # default untouched


class TestElasticMeshSizing:
    """Elastic sizing must never plan a group the mesh cannot tile."""

    def _scaling(self, **kw):
        from ray_tpu.train import ScalingConfig
        kw.setdefault("mesh_config", MeshConfig(dp=-1, fsdp=2))
        kw.setdefault("min_workers", 2)
        kw.setdefault("max_workers", 8)
        return ScalingConfig(resources_per_worker={"CPU": 1}, **kw)

    def test_fit_count_snaps_to_valid_world(self, monkeypatch):
        import ray_tpu
        from ray_tpu.train.scaling_policy import ElasticScalingPolicy
        policy = ElasticScalingPolicy(self._scaling())
        monkeypatch.setattr(ray_tpu, "available_resources",
                            lambda: {"CPU": 5.0})
        assert policy._fit_count() == 4  # 5 fit, snapped to 4

    def test_monitor_decision_skips_unusable_growth(self, monkeypatch):
        import ray_tpu
        from ray_tpu.train.scaling_policy import ElasticScalingPolicy
        policy = ElasticScalingPolicy(self._scaling())
        # One more CPU than the current world: 5 total, but 5 is not
        # tileable — growth the mesh cannot use is not worth a restart.
        monkeypatch.setattr(ray_tpu, "available_resources",
                            lambda: {"CPU": 1.0})
        assert policy.monitor_decision(4) is None

    def test_controller_drain_resize_snaps(self, tmp_path):
        """Regression: a drain leaving an un-factorable worker count
        must downsize to the nearest valid mesh world, not refuse (or
        form a group that dies in mesh construction)."""
        from ray_tpu.train import RunConfig
        from ray_tpu.train.controller import TrainController
        controller = TrainController(
            lambda: None, None,
            self._scaling(),
            RunConfig(name="snap", storage_path=str(tmp_path)))
        assert controller._valid_resize(3) == 2
        assert controller._valid_resize(4) == 4
        # Nothing valid at or below the target: snap up to the ceiling.
        assert controller._valid_resize(1) == 2

    def test_controller_worker_env_forces_host_devices(self, tmp_path):
        from ray_tpu.train import RunConfig, ScalingConfig
        from ray_tpu.train.controller import TrainController
        controller = TrainController(
            lambda: None, None,
            ScalingConfig(num_workers=2,
                          mesh_config=MeshConfig(
                              fsdp=-1, devices_per_worker=3)),
            RunConfig(name="env", storage_path=str(tmp_path)))
        env = controller._worker_env(0, 2)
        assert "--xla_force_host_platform_device_count=3" \
            in env["XLA_FLAGS"]

    def test_controller_resolved_axes_fallback(self, tmp_path):
        """Without a MeshConfig the resolved mesh is pure dp (the
        legacy path, now visible in Result.mesh / `ray-tpu status`)."""
        from ray_tpu.train import RunConfig, ScalingConfig
        from ray_tpu.train.controller import TrainController
        controller = TrainController(
            lambda: None, None, ScalingConfig(num_workers=4),
            RunConfig(name="dponly", storage_path=str(tmp_path)))
        axes = controller._resolved_axes(4)
        assert axes["dp"] == 4
        assert all(s == 1 for a, s in axes.items() if a != "dp")


def _build_meshes(desc_a: str, desc_b: str):
    import jax

    from ray_tpu.parallel import build_mesh
    devices = jax.devices()[:8]
    return (build_mesh(MeshConfig.parse(desc_a).spec_for(8), devices),
            build_mesh(MeshConfig.parse(desc_b).spec_for(8), devices))


_LOGICAL = {"w": ("embed", None), "stacked": ("layers", "embed", None),
            "b": (None,), "step": None}


def _host_tree():
    return {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
            "stacked": np.arange(256, dtype=np.float32).reshape(4, 8, 8),
            "b": np.arange(8, dtype=np.float32), "step": 7}


def _save_on_mesh(mesh, dirpath, rules=None):
    """Single-process world=1 sharded save: snapshot decomposes the jax
    Arrays through addressable_shards, recording global indexes."""
    from ray_tpu.checkpoint import format as F
    from ray_tpu.train.mesh.runtime import shard_tree
    host = _host_tree()
    tree = shard_tree({k: host[k] for k in ("w", "stacked", "b")},
                      {k: _LOGICAL[k] for k in ("w", "stacked", "b")},
                      mesh, rules=rules)
    tree["step"] = host["step"]
    snap = F.snapshot_tree(tree)
    index, blob = F.build_shard(snap, 0, 1, 0)
    F.write_shard(dirpath, index, blob, skeleton_pkl=snap.skeleton_pkl)
    manifest = F.build_manifest(dirpath, 0, 1,
                                metrics=R.save_metrics(mesh))
    F.commit_manifest(dirpath, manifest)
    return host


class TestMeshReshapeMatrix:
    """Checkpoint-format-level reshape restores, bit-exact across the
    {dp8 -> fsdp8, fsdp8 -> dp2xfsdp4, pp2xfsdp4 -> fsdp8} matrix."""

    @pytest.mark.parametrize("desc_a,desc_b", [
        ("dp8", "fsdp8"),
        ("fsdp8", "dp2xfsdp4"),
        ("pp2xfsdp4", "fsdp8"),
    ])
    def test_reshape_bit_exact(self, desc_a, desc_b, tmp_path):
        from ray_tpu.parallel.sharding import default_rules
        mesh_a, mesh_b = _build_meshes(desc_a, desc_b)

        def rules_for(desc):
            # pp meshes shard the stacked layer axis over pp (the GPipe
            # resident-stage layout, parallel/pipeline.py).
            return default_rules().replace(layers="pp") \
                if "pp" in desc else default_rules()

        host = _save_on_mesh(mesh_a, str(tmp_path), rules=rules_for(desc_a))
        shardings = R.sharding_tree(_LOGICAL, mesh_b,
                                    rules=rules_for(desc_b))
        out = R.restore_to_mesh(str(tmp_path), shardings)
        for key in ("w", "stacked", "b"):
            np.testing.assert_array_equal(np.asarray(out[key]), host[key])
        assert out["step"] == 7

    def test_reshape_counter_bumps_only_across_shapes(self, tmp_path):
        from ray_tpu.util import metrics as metrics_mod
        metrics_mod._reset_for_tests()
        mesh_a, mesh_b = _build_meshes("fsdp8", "dp2xfsdp4")
        _save_on_mesh(mesh_a, str(tmp_path))
        # Same-shape restore: no reshape.
        R.restore_to_mesh(str(tmp_path), R.sharding_tree(_LOGICAL, mesh_a))
        text = metrics_mod.prometheus_text()
        assert "ray_tpu_train_mesh_reshapes_total 1.0" not in text
        # Cross-shape restore: one reshape event.
        R.restore_to_mesh(str(tmp_path), R.sharding_tree(_LOGICAL, mesh_b))
        text = metrics_mod.prometheus_text()
        assert "ray_tpu_train_mesh_reshapes_total 1.0" in text
        metrics_mod._reset_for_tests()

    def test_param_shard_bytes_gauge(self, tmp_path):
        from ray_tpu.train.mesh.runtime import (addressable_param_bytes,
                                                shard_tree)
        mesh, _ = _build_meshes("fsdp8", "dp8")
        host = _host_tree()
        tree = shard_tree({"w": host["w"]}, {"w": ("embed", None)}, mesh)
        # Single process owns all 8 devices -> addressable == total, but
        # per-DEVICE bytes must be ~ total/8 for the sharded leaf.
        from ray_tpu.train.mesh.runtime import per_device_param_bytes
        per_dev = per_device_param_bytes(tree)
        assert len(per_dev) == 8
        assert all(b == host["w"].nbytes // 8 for b in per_dev.values())
        assert addressable_param_bytes(tree) == host["w"].nbytes

    def test_descriptor(self):
        assert R.mesh_descriptor({"dp": 2, "fsdp": 4, "tp": 1}) \
            == "dp2xfsdp4"
        assert R.mesh_descriptor({"dp": 1, "fsdp": 1}) == "single"
        assert R.mesh_descriptor({"pp": 2, "fsdp": 4}) == "pp2xfsdp4"


def _mesh_save_fn(config=None):
    import numpy as np

    import ray_tpu.train as train

    mesh = train.get_mesh()
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    b = np.arange(8, dtype=np.float32)
    tree = train.shard({"w": w, "b": b},
                       {"w": ("embed", None), "b": (None,)})
    train.save_checkpoint(tree, metrics={"step": 1})
    train.report({"fsdp": axes["fsdp"], "step": 1})


def _mesh_restore_fn(config=None):
    import jax
    import numpy as np

    import ray_tpu.train as train
    from ray_tpu.checkpoint.sharding import index_size
    from ray_tpu.train.mesh import reshape as R

    ctx = train.get_context()
    mesh = train.get_mesh()
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert axes["fsdp"] == 4, axes
    assert len(jax.devices()) == 4      # 2 workers x 2 forced devices
    assert jax.local_device_count() == 2

    logical = {"w": ("embed", None), "b": (None,)}
    tree = train.load_sharded(logical)
    assert tree is not None
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    # Bit-exact per addressable shard (device_get of the full global
    # array is impossible here: half of it lives on the peer process).
    for sh in tree["w"].addressable_shards:
        np.testing.assert_array_equal(np.asarray(sh.data), w[sh.index])
    # Ownership: this process's restore placement is a strict subset —
    # exactly its half of the rows — so it never read the peer's slices.
    box = R.process_index(
        R.sharding_tree(logical, mesh)["w"], w.shape)
    assert index_size(box) * 2 == w.size, box
    train.report({"step": 2, "rows": box[0][1] - box[0][0]})


class TestTrainerMeshE2E:
    def test_two_worker_reshape_restore(self, ray_start):
        """Save on a 2-process fsdp2 mesh (one device each), restore on
        a 2-process fsdp4 mesh (two forced host devices each): an
        elastic-style mesh reshape through the real trainer path."""
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
        with tempfile.TemporaryDirectory() as tmp:
            save = JaxTrainer(
                _mesh_save_fn,
                scaling_config=ScalingConfig(
                    num_workers=2,
                    mesh_config=MeshConfig(fsdp=-1)),
                run_config=RunConfig(name="mesh_e2e",
                                     storage_path=tmp)).fit()
            assert save.error is None
            assert save.mesh and save.mesh["fsdp"] == 2
            assert {r["metrics"].get("fsdp")
                    for r in save.all_reports} == {2}

            restore = JaxTrainer(
                _mesh_restore_fn,
                scaling_config=ScalingConfig(
                    num_workers=2,
                    mesh_config=MeshConfig(fsdp=-1,
                                           devices_per_worker=2)),
                run_config=RunConfig(name="mesh_e2e",
                                     storage_path=tmp)).fit()
            assert restore.error is None
            assert restore.mesh and restore.mesh["fsdp"] == 4
            rows = [r["metrics"]["rows"] for r in restore.all_reports
                    if "rows" in r["metrics"]]
            assert rows == [4, 4]  # each process owned half the rows

    def test_mesh_status_published(self, ray_start):
        from ray_tpu.train.mesh.runtime import (publish_mesh_status,
                                                read_mesh_status)
        publish_mesh_status("testrun", {"dp": 2, "fsdp": 4}, 2, 4)
        status = read_mesh_status()
        assert status is not None
        assert status["descriptor"] == "dp2xfsdp4"
        assert status["world"] == 2
        assert status["devices_per_worker"] == 4
