"""Job submission tests: manager, REST server + SDK client, CLI.

Reference analogs: dashboard/modules/job/tests/test_job_manager.py and
release job-submission smoke tests.
"""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job_submission import JobManager, JobStatus, JobSubmissionClient
from ray_tpu.job_submission.server import JobServer


class TestJobManager:
    def test_successful_job(self, ray_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c \"print('hello from job')\"")
        status = mgr.wait_until_finished(sid, timeout=60)
        assert status == JobStatus.SUCCEEDED
        assert "hello from job" in mgr.get_job_logs(sid)

    def test_failing_job(self, ray_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c 'import sys; sys.exit(3)'")
        assert mgr.wait_until_finished(sid, timeout=60) == JobStatus.FAILED
        assert "code 3" in mgr.get_job_info(sid).message

    def test_stop_job(self, ray_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
        time.sleep(0.5)
        assert mgr.get_job_status(sid) == JobStatus.RUNNING
        assert mgr.stop_job(sid)
        assert mgr.get_job_status(sid) == JobStatus.STOPPED

    def test_env_vars_runtime_env(self, ray_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=(f"{sys.executable} -c "
                        "\"import os; print(os.environ['MY_FLAG'])\""),
            runtime_env={"env_vars": {"MY_FLAG": "flag-value-42"}})
        assert mgr.wait_until_finished(sid, timeout=60) == JobStatus.SUCCEEDED
        assert "flag-value-42" in mgr.get_job_logs(sid)

    def test_duplicate_and_invalid_ids(self, ray_start):
        mgr = JobManager()
        sid = mgr.submit_job(entrypoint="true", submission_id="job-a")
        with pytest.raises(ValueError):
            mgr.submit_job(entrypoint="true", submission_id="job-a")
        with pytest.raises(ValueError):
            mgr.submit_job(entrypoint="true", submission_id="bad id;rm")
        mgr.wait_until_finished(sid, timeout=60)


class TestJobServerAndClient:
    @pytest.fixture()
    def client(self, ray_start):
        mgr = JobManager()
        server = JobServer(mgr, port=0)
        yield JobSubmissionClient(server.address)
        server.stop()

    def test_submit_status_logs(self, client):
        sid = client.submit_job(
            entrypoint=f"{sys.executable} -c \"print('via rest')\"")
        assert client.wait_until_finished(sid, 60) == "SUCCEEDED"
        assert "via rest" in client.get_job_logs(sid)
        jobs = client.list_jobs()
        assert any(j["submission_id"] == sid for j in jobs)

    def test_tail_and_stop(self, client):
        sid = client.submit_job(
            entrypoint=(f"{sys.executable} -u -c "
                        "\"import time\nfor i in range(100):\n"
                        "    print('tick', i, flush=True)\n"
                        "    time.sleep(0.1)\""))
        # Wait for output rather than a fixed sleep: under load the
        # interpreter can take >1s to boot, and stopping before the first
        # tick makes the log assertion racy.
        deadline = time.monotonic() + 30
        while "tick" not in client.get_job_logs(sid):
            assert time.monotonic() < deadline, "job never produced output"
            time.sleep(0.2)
        assert client.stop_job(sid)
        assert client.get_job_status(sid) == "STOPPED"
        assert "tick" in client.get_job_logs(sid)

    def test_cluster_status(self, client):
        s = client.cluster_status()
        assert s["nodes"] and "CPU" in s["total_resources"]
        # Operator-health fields for `ray-tpu status` (watchdog/goodput
        # are None until a training run has been observed, but the keys
        # are always present).
        assert "goodput" in s and "watchdog" in s

    def test_cluster_stacks_and_debug_dump(self, client):
        # `ray-tpu stack` surface: the driver record is always there.
        dump = client._request("GET", "/api/cluster/stacks?timeout_s=3")
        assert any(r.get("is_driver") for r in dump["stacks"])
        assert "unresponsive" in dump
        # `ray-tpu debug dump` surface: writes a bundle, returns its path.
        out = client._request("POST",
                              "/api/cluster/debug_dump?reason=resttest")
        assert os.path.isdir(out["path"])
        assert "resttest" in os.path.basename(out["path"])
        assert "manifest.json" in os.listdir(out["path"])

    def test_missing_job_404(self, client):
        with pytest.raises(RuntimeError, match="404"):
            client.get_job_status("nonexistent")


@pytest.mark.slow
class TestCli:
    def test_start_submit_status_stop(self, tmp_path):
        addr_file = str(tmp_path / "head_address")
        env = dict(os.environ, PYTHONPATH="/root/repo",
                   JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")

        def cli(*args, check=True, timeout=90):
            r = subprocess.run(
                [sys.executable, "-m", "ray_tpu.scripts.cli", *args],
                capture_output=True, text=True, env=env, timeout=timeout)
            if check:
                assert r.returncode == 0, r.stdout + r.stderr
            return r

        r = cli("start", "--head", "--port", "0", "--num-cpus", "2",
                "--address-file", addr_file)
        assert "head started at" in r.stdout
        address = json.load(open(addr_file))["address"]
        try:
            r = cli("status", "--address", address)
            assert "nodes: 1" in r.stdout
            r = cli("job", "submit", "--address", address, "--",
                    sys.executable, "-c", "\"print('cli job ran')\"")
            assert "cli job ran" in r.stdout
            assert "SUCCEEDED" in r.stdout
            r = cli("job", "list", "--address", address)
            assert "SUCCEEDED" in r.stdout
        finally:
            cli("stop", "--address-file", addr_file)
        deadline = time.monotonic() + 10
        while os.path.exists(addr_file) and time.monotonic() < deadline:
            time.sleep(0.2)
        assert not os.path.exists(addr_file)
