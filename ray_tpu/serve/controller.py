"""Serve controller: reconciliation + autoscaling control loop.

Reference: the ServeController actor's update loops
(python/ray/serve/_private/deployment_state.py:2795 — reconcile target vs
running replicas, recover dead ones) and request-based autoscaling
(serve/autoscaling_policy.py + _private/autoscaling_state.py — desired =
total ongoing requests / target per replica, clamped with up/downscale
delays).  One background thread reconciles every deployment; replica-set
changes are pushed to routers through the long-poll broker.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from .long_poll import LongPollBroker


@dataclass
class AutoscalingConfig:
    """reference: serve/config.py AutoscalingConfig."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 1.0
    downscale_delay_s: float = 5.0


class ServeController:
    """Reconciles deployments to their targets (self-healing + autoscale)."""

    def __init__(self, deployments: Dict, app_lock: threading.Lock,
                 interval_s: float = 0.25):
        self.deployments = deployments  # name -> _DeploymentState (live dict)
        self._app_lock = app_lock
        self.broker = LongPollBroker()
        self.interval_s = interval_s
        self._stop = threading.Event()
        # Autoscaling decision memory: name -> (direction, since_ts)
        self._pending_scale: Dict[str, tuple] = {}
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-controller", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- control loop -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._reconcile_all()
            except Exception:
                import traceback
                traceback.print_exc()

    def _reconcile_all(self) -> None:
        with self._app_lock:
            states = list(self.deployments.values())
        for state in states:
            if state.stopped:
                continue
            self._health_check(state)
            self._autoscale(state)
            self._reconcile(state)

    # -- pieces -------------------------------------------------------------

    def _health_check(self, state) -> None:
        """Drop replicas whose actors died (reference: deployment_state
        replica recovery); the reconcile step then backfills."""
        from .._private.api import _control
        dead = []
        with state._lock:
            replicas = list(state.replicas)
        for r in replicas:
            try:
                actor_state = _control("actor_state", r._actor_id.binary())
            except Exception:
                actor_state = None
            if actor_state in ("DEAD",):
                dead.append(r)
        if dead:
            with state._lock:
                for r in dead:
                    if r in state.replicas:
                        i = state.replicas.index(r)
                        state.replicas.pop(i)
                        state.inflight.pop(id(r), None)
            self._publish(state)

    def _autoscale(self, state) -> None:
        cfg: Optional[AutoscalingConfig] = state.deployment.autoscaling_config
        if cfg is None:
            return
        with state._lock:
            n = len(state.replicas)
            total_inflight = sum(state.inflight.values())
        if n == 0:
            return
        desired = math.ceil(total_inflight / max(cfg.target_ongoing_requests,
                                                 1e-6))
        desired = max(min(desired, cfg.max_replicas), cfg.min_replicas)
        if desired == state.target_replicas:
            self._pending_scale.pop(state.deployment.name, None)
            return
        direction = "up" if desired > state.target_replicas else "down"
        delay = cfg.upscale_delay_s if direction == "up" \
            else cfg.downscale_delay_s
        key = state.deployment.name
        pending = self._pending_scale.get(key)
        now = time.monotonic()
        if pending is None or pending[0] != direction:
            self._pending_scale[key] = (direction, now)
            return
        if now - pending[1] >= delay:
            state.target_replicas = desired
            self._pending_scale.pop(key, None)

    def _reconcile(self, state) -> None:
        """Start/stop replicas until running == target (reference:
        deployment_state.py reconciliation).  Backfill waits for replica
        readiness and backs off exponentially when creation keeps failing
        (no unbounded actor crash loops)."""
        if state.stopped:
            return
        with state._lock:
            n = len(state.replicas)
            target = state.target_replicas
        changed = False
        now = time.monotonic()
        while n < target and now >= state.backfill_not_before:
            try:
                state.add_replica(wait_ready=True)
                state.backfill_backoff_s = 0.5
                changed = True
            except Exception:
                state.backfill_not_before = now + state.backfill_backoff_s
                state.backfill_backoff_s = min(
                    state.backfill_backoff_s * 2, 30.0)
                break
            n += 1
        while n > target:
            state.remove_replica()
            changed = True
            n -= 1
        if changed:
            self._publish(state)

    def _publish(self, state) -> None:
        with state._lock:
            snapshot = list(state.replicas)
        self.broker.publish(state.deployment.name, snapshot)
