"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference analog: autoscaler v2 (python/ray/autoscaler/v2/autoscaler.py:51
Autoscaler, v2/scheduler.py:822 ResourceDemandScheduler, declarative
instance_manager/) fed by the GCS resource-demand view.  Here the
reconciler reads the scheduler's unplaced shapes directly, bin-packs them
onto configured node types, and drives a NodeProvider to converge —
LocalSubprocessProvider boots real NodeServer processes (the test story,
reference: FakeMultiNodeProvider autoscaler/_private/fake_multi_node/
node_provider.py:237); TPUPodProvider is the GKE/QueuedResources-shaped
seam for real TPU fleets.
"""

from .autoscaler import (AUTOSCALER_KV_KEY, Autoscaler, AutoscalerConfig,
                         NodeTypeConfig)
from .policy import (GoodputAutoscalePolicy, GoodputPolicyConfig,
                     ScaleDecision)
from .providers import (LocalSubprocessProvider, NodeProvider,
                        TPUPodProvider)

__all__ = [
    "Autoscaler", "AutoscalerConfig", "AUTOSCALER_KV_KEY",
    "NodeTypeConfig", "NodeProvider", "GoodputAutoscalePolicy",
    "GoodputPolicyConfig", "ScaleDecision",
    "LocalSubprocessProvider", "TPUPodProvider",
]
