"""Per-worker training context + report API.

Reference analog: ray.train.get_context()/report
(reference: python/ray/train/v2/api/train_fn_utils.py:23 report,
.../execution/context.py).  report() publishes metrics (and optionally a
checkpoint) to the controller through the runtime KV store; the rank-0
checkpoint is committed by the CheckpointManager.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

from ._checkpoint import Checkpoint

_context: Optional["TrainContext"] = None


class TrainContext:
    def __init__(self, run_id: str, rank: int, world_size: int,
                 local_rank: int, storage_path: str,
                 experiment_name: str,
                 latest_checkpoint: Optional[str] = None,
                 slice_id: int = 0, num_slices: int = 1,
                 checkpoint_options: Optional[Dict[str, Any]] = None,
                 mesh_info: Optional[Dict[str, Any]] = None):
        self.run_id = run_id
        self._rank = rank
        self._world_size = world_size
        self._local_rank = local_rank
        self.storage_path = storage_path
        self.experiment_name = experiment_name
        self._latest_checkpoint = latest_checkpoint
        self.slice_id = slice_id
        self.num_slices = num_slices
        self._ckpt_options = dict(checkpoint_options or {})
        self._ckpt_client = None
        self._report_seq = 0
        # Unique per worker incarnation: keeps report keys distinct across
        # failure-recovery restarts (seq restarts at 0 in a fresh worker).
        import uuid as _uuid
        self._incarnation = _uuid.uuid4().hex[:8]
        # Telemetry: report-to-report interval = one observed step.  The
        # wall stamp anchors the timeline span; the interval itself is
        # measured on the monotonic clock (NTP-immune).
        self._last_report_wall = time.time()
        self._last_report_mono = time.monotonic()
        # Drain protocol (preemption notice): report() polls the
        # controller's generation-tagged drain request and answers it
        # once with an urgent checkpoint flush + ack.
        self._generation = self._ckpt_options.get("generation")
        self._last_drain_check_mono = 0.0
        self._drain_acked = False
        # Mesh runtime (train/mesh): the controller resolves the axis
        # sizes for THIS incarnation's world; the worker builds the
        # global jax mesh lazily on first get_mesh()/shard() use.
        self._mesh_info = dict(mesh_info or {})
        self._mesh = None

    # -- mesh runtime -------------------------------------------------------

    def mesh(self):
        """The group's global SPMD mesh (built on first use over the
        jax.distributed world's full device set; falls back to a pure
        data-parallel mesh when no MeshConfig was configured)."""
        if self._mesh is None:
            import jax

            from ..parallel.mesh import MeshSpec
            from .mesh.runtime import build_worker_mesh
            axes = self._mesh_info.get("axes") or {}
            num_slices = int(self._mesh_info.get("num_slices",
                                                 self.num_slices) or 1)
            if axes:
                spec = MeshSpec(num_slices=num_slices,
                                **{a: int(s) for a, s in axes.items()})
            else:
                spec = MeshSpec(dp=len(jax.devices()),
                                num_slices=num_slices)
            self._mesh = build_worker_mesh(spec)
        return self._mesh

    def sharding_rules(self):
        """Logical-axis rules: defaults + the MeshConfig's overrides
        (same merge as MeshConfig.sharding_rules — one implementation,
        so worker-side resolution can never drift from config-side)."""
        from .mesh.config import rules_with_overrides
        return rules_with_overrides(self._mesh_info.get("rules"))

    def get_world_rank(self) -> int:
        return self._rank

    def get_world_size(self) -> int:
        return self._world_size

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_checkpoint(self) -> Optional[Checkpoint]:
        if self._latest_checkpoint and os.path.exists(self._latest_checkpoint):
            return Checkpoint(self._latest_checkpoint)
        return None

    # -- sharded checkpoint subsystem ---------------------------------------

    def checkpoint_client(self):
        """This worker's save/restore client (ray_tpu.checkpoint)."""
        if self._ckpt_client is None:
            from ..checkpoint.manager import (WorkerCheckpointClient,
                                             _dir_step)
            opts = self._ckpt_options
            start = 0
            if self._latest_checkpoint:
                # Resume the auto-step sequence past the restored
                # checkpoint so a restarted worker never overwrites a
                # committed step directory.
                s = _dir_step(os.path.basename(
                    os.path.normpath(self._latest_checkpoint)))
                if s is not None:
                    start = s + 1
            self._ckpt_client = WorkerCheckpointClient(
                run_id=self.run_id, rank=self._rank,
                world_size=self._world_size,
                run_root=os.path.join(os.path.abspath(self.storage_path),
                                      self.experiment_name),
                experiment=self.experiment_name,
                async_save=opts.get("async_save", True),
                max_inflight=opts.get("max_inflight", 2),
                emergency_replica=opts.get("emergency_replica", False),
                initial_step=start,
                generation=opts.get("generation"))
        return self._ckpt_client

    def teardown(self) -> None:
        """Flush + close the async checkpoint writer (run at the end of
        the train fn so every submitted save acks before the worker
        reports success)."""
        if self._ckpt_client is not None:
            self._ckpt_client.close()
            self._ckpt_client = None


def set_context(ctx: Optional[TrainContext]) -> None:
    global _context
    _context = ctx


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a train worker")
    return _context


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ checkpoint) from inside the train fn."""
    ctx = get_context()
    ctx._report_seq += 1
    from .._private.api import _control
    from ..profiler import attribution
    from ..util import telemetry
    now = time.time()
    now_mono = time.monotonic()
    ckpt_s = telemetry.pop_checkpoint_seconds()
    # Step-phase attribution: whatever this step declared through
    # train.step_phase(), plus checkpoint-blocking time and the derived
    # unattributed remainder ("other").  seq 1's window is init/compile,
    # not a step — no remainder is derived for it.
    step_s = (now_mono - ctx._last_report_mono) \
        if ctx._report_seq > 1 else None
    phases = attribution.finalize_step_phases(
        attribution.pop_phases(), step_s, ckpt_s)
    payload = {
        "metrics": dict(metrics),
        "rank": ctx.get_world_rank(),
        "seq": ctx._report_seq,
        "time": now,
        # Same-process monotonic stamp: the watchdog measures this
        # rank's report-to-report intervals from it (wall time steps
        # under NTP; deltas of one process's monotonic clock do not).
        # The incarnation scopes the stamp: a restarted worker's clock
        # has a different base and must not be differenced.
        "mono": now_mono,
        "incarnation": ctx._incarnation,
        # Worker pid: lets the watchdog's stack auto-capture mark which
        # process record belongs to a flagged rank.
        "pid": os.getpid(),
        "checkpoint_dir": checkpoint.path if checkpoint else None,
        # Checkpoint seconds inside this report window (goodput
        # reattribution at the controller).
        "ckpt_seconds": ckpt_s,
        # Per-phase step decomposition (data_wait/h2d/compute/.../other):
        # the controller aggregates Result.step_phases from rank 0 and
        # reattributes data-wait out of goodput's productive phase.
        "phases": phases,
    }
    _note_step(ctx, now, now_mono, metrics, phases)
    _control("kv_put",
             f"train/{ctx.run_id}/report/{ctx.get_world_rank()}/"
             f"{ctx._incarnation}/{ctx._report_seq}",
             pickle.dumps(payload))
    # Progress published first, THEN answer any pending drain request:
    # the controller sees this step's checkpoint registration before the
    # urgent-flush ack completes its ack set.
    _maybe_drain_flush(ctx)


def save_checkpoint(tree: Any, metrics: Optional[Dict[str, Any]] = None,
                    *, shard_spec=None, step: Optional[int] = None,
                    sync: Optional[bool] = None) -> str:
    """Save this rank's shards of ``tree`` through the distributed
    checkpoint subsystem; returns the checkpoint directory.

    With async saves (the default, ``CheckpointConfig.async_save``), the
    call blocks only for the device->host snapshot — serialization and
    the write happen on a background thread while training continues —
    and the checkpoint becomes ``latest`` only after EVERY rank's shard
    landed and the coordinator committed the manifest atomically.
    ``shard_spec(key, leaf) -> (global_shape, index)`` declares the slice
    of a global array this rank holds (see
    ``ray_tpu.checkpoint.even_shard_spec``)."""
    ctx = get_context()
    if ctx._mesh is not None:
        # Stamp the saving mesh's shape so a later restore can tell a
        # same-shape resume from a mesh reshape (reshape counter).
        from .mesh.reshape import save_metrics as _mesh_save_metrics
        metrics = _mesh_save_metrics(ctx._mesh, metrics)
    return ctx.checkpoint_client().save(tree, metrics=metrics,
                                        shard_spec=shard_spec, step=step,
                                        sync=sync)


def load_checkpoint(placement=None) -> Optional[Any]:
    """Restore the latest committed checkpoint's pytree, resharded to
    ``placement(key, global_shape) -> index`` (None = full arrays; see
    ``ray_tpu.checkpoint.even_placement``).  Prefers in-memory emergency
    replica shards over disk when replication is enabled.  Returns None
    when the run has no checkpoint yet."""
    ctx = get_context()
    if not ctx._latest_checkpoint or \
            not os.path.exists(ctx._latest_checkpoint):
        return None
    return ctx.checkpoint_client().load(ctx._latest_checkpoint,
                                        placement=placement)


def get_mesh():
    """The worker group's global SPMD mesh (inside a train fn).  Built
    from the controller-resolved MeshConfig axes; without a MeshConfig
    it is a pure data-parallel mesh over every device in the world."""
    return get_context().mesh()


def shard(tree: Any, logical_tree: Any):
    """Place a pytree of host arrays onto the group mesh per a parallel
    pytree of logical-axis tuples (``parallel.sharding`` rules + the
    MeshConfig's overrides).  Every process passes the same full host
    values; each device materializes only its shard."""
    ctx = get_context()
    from .mesh.runtime import shard_tree
    return shard_tree(tree, logical_tree, ctx.mesh(),
                      rules=ctx.sharding_rules())


def shard_batch(batch: Any):
    """Place this process's LOCAL batch rows onto the mesh's data axes
    (leading dim over (dp, fsdp), seq over sp when sized): together the
    processes' rows form one global batch array."""
    ctx = get_context()
    from .mesh.runtime import shard_batch_tree
    return shard_batch_tree(batch, ctx.mesh(),
                            rules=ctx.sharding_rules())


def load_sharded(logical_tree: Any) -> Optional[Any]:
    """Restore the latest committed checkpoint directly onto the group
    mesh (mesh-reshape restore: the saved mesh shape may differ — each
    process reads only the index slices its devices own).  Returns None
    when the run has no checkpoint yet."""
    ctx = get_context()
    if not ctx._latest_checkpoint or \
            not os.path.exists(ctx._latest_checkpoint):
        return None
    from .mesh.reshape import restore_to_mesh, sharding_tree
    shardings = sharding_tree(logical_tree, ctx.mesh(),
                              rules=ctx.sharding_rules())
    client = ctx.checkpoint_client()
    return restore_to_mesh(
        ctx._latest_checkpoint, shardings,
        loader=lambda path, placement: client.load(path,
                                                   placement=placement),
        # One reshape event per GROUP restore, not one per process.
        count_reshape=ctx.get_world_rank() == 0)


def drain_key(run_id: str) -> str:
    """KV key the controller publishes a drain request under."""
    return f"train/{run_id}/drain"


def drain_ack_prefix(run_id: str, generation=None) -> str:
    """Ack-key prefix — ONE source of truth for the protocol's key
    layout (the controller polls and GCs by this prefix; generation=None
    spans every generation for the post-teardown sweep)."""
    base = f"train/{run_id}/drain_ack/"
    return base if generation is None else f"{base}{generation}/"


def drain_ack_key(run_id: str, generation, rank: int) -> str:
    return drain_ack_prefix(run_id, generation) + str(rank)


def _maybe_drain_flush(ctx: "TrainContext") -> None:
    """Worker half of the drain protocol: when the controller posts a
    drain request for this generation, flush the async checkpoint writer
    (every submitted save publishes, acks, and pushes its emergency RAM
    replica) and ack — the urgent checkpoint that makes a preemption a
    planned downsize instead of lost work.  Rate-limited so fast step
    loops don't pay a KV round-trip per report."""
    now_mono = time.monotonic()
    if ctx._drain_acked or \
            now_mono - ctx._last_drain_check_mono < 0.25:
        return
    ctx._last_drain_check_mono = now_mono
    from .._private.api import _control
    raw = _control("kv_get", drain_key(ctx.run_id))
    if raw is None:
        return
    try:
        req = pickle.loads(raw)
    except Exception:
        return
    if req.get("generation") != ctx._generation:
        return  # stale request from a torn-down incarnation
    ctx._drain_acked = True
    budget_s = max(1.0, float(req.get("budget_s", 30.0)))
    err = None
    try:
        if ctx._ckpt_client is not None:
            ctx._ckpt_client.flush(timeout=budget_s)
    except Exception as e:  # noqa: BLE001 — reported in the ack
        err = f"{type(e).__name__}: {e}"
    _control("kv_put",
             drain_ack_key(ctx.run_id, ctx._generation,
                           ctx.get_world_rank()),
             pickle.dumps({"rank": ctx.get_world_rank(),
                           "incarnation": ctx._incarnation,
                           "flushed": ctx._ckpt_client is not None,
                           "error": err}))
    # Park until the controller tears this group down: the ack means
    # "my work is durable — take me down".  Stepping on would only
    # manufacture an uncommitted tail (work the restart re-executes as
    # lost) and race fresh saves/pins against the teardown kill.
    # Bounded: if the drain is cancelled (key gone) or the deadline
    # passes with this worker still alive, resume training.
    deadline = time.monotonic() + budget_s + 15.0
    while time.monotonic() < deadline:
        if _control("kv_get", drain_key(ctx.run_id)) is None:
            break
        time.sleep(0.2)


def _note_step(ctx: "TrainContext", now: float, now_mono: float,
               metrics: Dict[str, Any],
               phases: Optional[Dict[str, float]] = None) -> None:
    """Built-in train metrics from the report stream: each rank-0
    report-to-report interval is one step (histogram + timeline span);
    token counts ride along when the user metrics carry a tokens key."""
    from ..profiler import attribution
    from ..util import telemetry
    telemetry.inc("ray_tpu_train_reports_total")
    for key in ("tokens", "num_tokens", "tokens_per_step"):
        v = metrics.get(key)
        if isinstance(v, (int, float)) and v > 0:
            telemetry.inc("ray_tpu_train_tokens_total", v)
            break
    # Per-device HBM used/peak gauges (rate-limited; absent on backends
    # without memory_stats) — creeping HBM is a silent step-time killer.
    attribution.note_hbm_gauges()
    # seq 1 measures from context construction — that window is
    # init/JIT compile, not a step (the controller's goodput tracker
    # accounts it as "init"); report-to-report starts at seq 2.
    if ctx.get_world_rank() == 0 and ctx._report_seq > 1:
        dur = now_mono - ctx._last_report_mono
        if dur > 0:
            telemetry.observe("ray_tpu_train_step_seconds", dur)
            for phase, seconds in (phases or {}).items():
                telemetry.observe("ray_tpu_train_step_phase_seconds",
                                  seconds, tags={"phase": phase})
            # Span: wall anchor for position, monotonic length.
            telemetry._emit_span(
                "train_step", "train", ctx._last_report_wall,
                ctx._last_report_wall + dur,
                extra={"seq": ctx._report_seq, "run_id": ctx.run_id,
                       "phases": {k: round(v, 6)
                                  for k, v in (phases or {}).items()}})
    ctx._last_report_wall = now
    ctx._last_report_mono = now_mono
