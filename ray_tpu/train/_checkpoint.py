"""Checkpoints: directory-backed, with pytree helpers and a manager.

Reference analog: Checkpoint (reference: python/ray/train/_checkpoint.py:56,
fsspec directory URI) and CheckpointManager (reference:
python/ray/train/v2/_internal/execution/checkpoint/checkpoint_manager.py:98
— rank-0 commit, top-k retention).  Round-1 storage is a local/shared
filesystem path; pytrees serialize via pickled host numpy (orbax adapter:
``save_pytree(..., use_orbax=True)``).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional


class Checkpoint:
    """Handle to a checkpoint directory (reference: train/_checkpoint.py:56)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    # -- pytree convenience -------------------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str,
                    use_orbax: bool = False) -> "Checkpoint":
        os.makedirs(path, exist_ok=True)
        save_pytree(tree, path, use_orbax=use_orbax)
        return cls(path)

    def load_pytree(self, use_orbax: bool = False) -> Any:
        return load_pytree(self.path, use_orbax=use_orbax)

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_pytree(tree: Any, path: str, use_orbax: bool = False) -> None:
    """Device arrays -> host numpy -> disk."""
    import jax
    import numpy as np
    t0 = time.perf_counter()
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
    if use_orbax:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "orbax"), host)
    else:
        with open(os.path.join(path, "pytree.pkl"), "wb") as f:
            pickle.dump(host, f, protocol=5)
    _note_ckpt("save", time.perf_counter() - t0)


def load_pytree(path: str, use_orbax: bool = False) -> Any:
    t0 = time.perf_counter()
    if use_orbax:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        out = ckptr.restore(os.path.join(path, "orbax"))
    else:
        with open(os.path.join(path, "pytree.pkl"), "rb") as f:
            out = pickle.load(f)
    _note_ckpt("restore", time.perf_counter() - t0)
    return out


def _note_ckpt(op: str, seconds: float) -> None:
    try:
        from ..util import telemetry
    except Exception:
        return
    telemetry.observe("ray_tpu_train_checkpoint_seconds", seconds,
                      tags={"op": op})
    telemetry.note_checkpoint_seconds(seconds)


class CheckpointManager:
    """Tracks committed checkpoints under <storage>/<experiment>/.

    Commit protocol: a checkpoint directory is durable once the manager
    writes its entry into ``checkpoints.json`` (rank-0 report drives this;
    reference: checkpoint_manager.py rank-0-commit + _latest marker).
    """

    def __init__(self, storage_path: str, experiment_name: str,
                 num_to_keep: Optional[int] = None):
        self.root = os.path.join(os.path.abspath(storage_path),
                                 experiment_name)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self._index_path = os.path.join(self.root, "checkpoints.json")
        self._entries: List[Dict[str, Any]] = []
        if os.path.exists(self._index_path):
            with open(self._index_path) as f:
                self._entries = json.load(f)

    def checkpoint_dir(self, step: int) -> str:
        return os.path.join(self.root, f"checkpoint_{step:06d}")

    def register(self, path: str, metrics: Dict[str, Any]) -> None:
        self._entries.append({
            "path": os.path.abspath(path),
            "metrics": {k: v for k, v in metrics.items()
                        if isinstance(v, (int, float, str, bool))},
            "time": time.time(),
        })
        self._flush()
        self._enforce_retention()

    def latest(self) -> Optional[str]:
        return self._entries[-1]["path"] if self._entries else None

    def best(self, metric: str, mode: str = "min") -> Optional[str]:
        scored = [e for e in self._entries if metric in e["metrics"]]
        if not scored:
            return None
        pick = min if mode == "min" else max
        return pick(scored, key=lambda e: e["metrics"][metric])["path"]

    def all_entries(self) -> List[Dict[str, Any]]:
        return list(self._entries)

    def _flush(self) -> None:
        tmp = self._index_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._entries, f, indent=1)
        os.replace(tmp, self._index_path)

    def _enforce_retention(self) -> None:
        if not self.num_to_keep:
            return
        while len(self._entries) > self.num_to_keep:
            victim = self._entries.pop(0)
            self._flush()
            if os.path.isdir(victim["path"]):
                shutil.rmtree(victim["path"], ignore_errors=True)
