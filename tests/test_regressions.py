"""Regression tests for bugs found in review."""

import numpy as np
import pytest

import ray_tpu


class TestRegressions:
    def test_two_large_returns_no_shm_collision(self, ray_start):
        """ObjectIDs differing only in return index must not collide."""
        @ray_tpu.remote(num_returns=2)
        def two_big():
            return (np.zeros(500_000, dtype=np.float64),
                    np.ones(500_000, dtype=np.float64))
        a, b = two_big.remote()
        va, vb = ray_tpu.get([a, b])
        assert va.sum() == 0 and vb.sum() == 500_000

    def test_two_large_puts(self, ray_start):
        r1 = ray_tpu.put(np.zeros(1_000_000))
        r2 = ray_tpu.put(np.ones(1_000_000))
        assert ray_tpu.get(r1).sum() == 0
        assert ray_tpu.get(r2).sum() == 1_000_000

    def test_wait_num_returns_validation(self, ray_start):
        r = ray_tpu.put(1)
        with pytest.raises(ValueError):
            ray_tpu.wait([r], num_returns=2)

    def test_pending_pg_schedules_when_capacity_frees(self, ray_start):
        """A PG that doesn't fit initially must become CREATED once the
        blocking tasks release their resources."""
        import time

        @ray_tpu.remote(num_cpus=4)
        def hog():
            time.sleep(1.0)
            return "done"
        busy = hog.remote()
        time.sleep(0.2)  # let it get dispatched
        pg = ray_tpu.placement_group([{"CPU": 3}], strategy="PACK")
        assert not pg.ready(timeout=0.1)  # still pending while hog runs
        assert ray_tpu.get(busy, timeout=30) == "done"
        assert pg.ready(timeout=10)
        ray_tpu.remove_placement_group(pg)

    def test_actor_death_cause_reported(self, ray_start):
        @ray_tpu.remote
        class Broken:
            def __init__(self):
                raise KeyError("the-secret-reason")

            def m(self):
                return 1
        b = Broken.remote()
        import time
        for _ in range(100):
            states = {a["class_name"]: a for a in
                      ray_tpu._private.runtime.driver_runtime()
                      .ctl_list_actors()}
            if states.get("Broken", {}).get("state") == "DEAD":
                break
            time.sleep(0.1)
        info = ray_tpu._private.runtime.driver_runtime().controller
        dead = [a for a in info.actors.values() if a.class_name == "Broken"]
        assert dead and "the-secret-reason" in (dead[0].death_cause or "")


class TestChipLifecycle:
    def test_chip_env_and_pool_recovery(self, ray_start_isolated):
        """Sequential TPU tasks each get a full fresh grant; chips return
        to the pool only after the dedicated worker dies."""
        import ray_tpu as rt

        @rt.remote(num_tpus=2, num_cpus=0)
        def chips():
            import os
            return os.environ.get("TPU_VISIBLE_CHIPS")
        # ray_start_isolated has no TPU resource; make a fresh runtime.
        rt.shutdown()
        rt.init(num_cpus=4, num_tpus=4)
        g1 = rt.get(chips.remote(), timeout=120)
        g2 = rt.get(chips.remote(), timeout=120)
        g3 = rt.get(chips.remote(), timeout=120)
        for g in (g1, g2, g3):
            assert g is not None and len(g.split(",")) == 2, g
        # Three sequential 2-chip grants out of 4 chips only work if the
        # dispatch retry waits for dying workers to free their chips.
