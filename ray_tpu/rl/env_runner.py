"""Rollout layer: EnvRunner (vector env + module inference) and the remote
fan-out EnvRunnerGroup.

Reference: rllib/env/single_agent_env_runner.py:66 (SingleAgentEnvRunner —
vector envs, module forward, episode postprocessing via connectors) and
rllib/env/env_runner_group.py:70 (EnvRunnerGroup — remote runners,
``sample`` fan-out with ray.get, ``sync_weights`` broadcast).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import VectorEnv
from .rl_module import DiscretePolicyModule, RLModuleSpec


class EnvRunner:
    """Collects fixed-length rollout batches with the current policy."""

    def __init__(self, env_creator: Callable, *, num_envs: int = 4,
                 module_spec: Optional[RLModuleSpec] = None,
                 seed: int = 0, explore: bool = True):
        import jax

        self.vec = VectorEnv(env_creator, num_envs, seed=seed)
        self.spec = module_spec or RLModuleSpec(
            self.vec.observation_dim, self.vec.num_actions)
        self.module = DiscretePolicyModule(self.spec)
        self.explore = explore
        self._key = jax.random.key(seed)
        self.params = self.module.init(jax.random.key(seed + 1))
        self._obs = self.vec.reset()
        # Episode-return bookkeeping for metrics.
        self._ep_returns = np.zeros(num_envs, np.float64)
        self._ep_lens = np.zeros(num_envs, np.int64)
        self._finished_returns: List[float] = []
        self._finished_lens: List[int] = []

        self._explore_fn = jax.jit(self.module.forward_exploration)
        self._infer_fn = jax.jit(self.module.forward_inference)
        self._value_fn = jax.jit(
            lambda p, o: self.module.forward_train(p, o)["value"])

    # -- weights --------------------------------------------------------- #

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params}

    def set_state(self, state: Dict[str, Any]) -> bool:
        self.params = state["params"]
        return True

    # -- sampling -------------------------------------------------------- #

    def sample(self, num_steps: int = 256) -> Dict[str, np.ndarray]:
        """Rollout ``num_steps`` per sub-env; returns time-major flattened
        arrays plus bootstrap values for GAE."""
        import jax

        n, d = self.vec.num_envs, self.vec.observation_dim
        obs_buf = np.empty((num_steps, n, d), np.float32)
        act_buf = np.empty((num_steps, n), np.int32)
        logp_buf = np.empty((num_steps, n), np.float32)
        val_buf = np.empty((num_steps, n), np.float32)
        rew_buf = np.empty((num_steps, n), np.float32)
        done_buf = np.empty((num_steps, n), bool)
        term_buf = np.empty((num_steps, n), bool)
        # V(final_obs) for truncated boundaries (0 elsewhere): the GAE
        # bootstrap for episodes cut by time limits, not by termination.
        boot_buf = np.zeros((num_steps, n), np.float32)

        for t in range(num_steps):
            self._key, sub = jax.random.split(self._key)
            if self.explore:
                actions, logp, values = self._explore_fn(
                    self.params, self._obs, sub)
            else:
                actions = self._infer_fn(self.params, self._obs)
                logp = np.zeros(n, np.float32)
                values = np.zeros(n, np.float32)
            actions = np.asarray(actions)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            self._obs, rewards, dones, terms, final_obs = \
                self.vec.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones
            term_buf[t] = terms
            truncs = dones & ~terms
            if self.explore and truncs.any():
                vals = np.asarray(self._value_fn(self.params, final_obs))
                boot_buf[t, truncs] = vals[truncs]
            self._ep_returns += rewards
            self._ep_lens += 1
            for i in np.nonzero(dones)[0]:
                self._finished_returns.append(float(self._ep_returns[i]))
                self._finished_lens.append(int(self._ep_lens[i]))
                self._ep_returns[i] = 0.0
                self._ep_lens[i] = 0

        # Bootstrap value for the final observation of each sub-env.
        if self.explore:
            self._key, sub = jax.random.split(self._key)
            _, _, last_val = self._explore_fn(self.params, self._obs, sub)
            last_val = np.asarray(last_val)
        else:
            last_val = np.zeros(n, np.float32)
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "terminateds": term_buf, "bootstrap_values": boot_buf,
            "last_values": last_val,
        }

    def metrics(self, window: int = 100) -> Dict[str, float]:
        rets = self._finished_returns[-window:]
        lens = self._finished_lens[-window:]
        return {
            "episode_return_mean": float(np.mean(rets)) if rets else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "num_episodes": len(self._finished_returns),
        }

    def ping(self) -> str:
        return "ok"


class EnvRunnerGroup:
    """Local-or-remote set of EnvRunners (reference: env_runner_group.py:70).

    ``num_env_runners=0`` keeps one local runner (the rllib convention for
    debugging); otherwise runners are actors sampled in parallel.
    """

    def __init__(self, env_creator: Callable, *, num_env_runners: int = 0,
                 num_envs_per_runner: int = 4,
                 module_spec: Optional[RLModuleSpec] = None, seed: int = 0,
                 runner_resources: Optional[Dict[str, float]] = None):
        self.num_env_runners = num_env_runners
        if num_env_runners == 0:
            self.local = EnvRunner(env_creator, num_envs=num_envs_per_runner,
                                   module_spec=module_spec, seed=seed)
            self.remotes = []
        else:
            import ray_tpu
            self.local = None
            cls = ray_tpu.remote(EnvRunner)
            opts = {"num_cpus": 1}
            if runner_resources:
                opts["resources"] = runner_resources
            self.remotes = [
                cls.options(**opts).remote(
                    env_creator, num_envs=num_envs_per_runner,
                    module_spec=module_spec, seed=seed + 1000 * (i + 1))
                for i in range(num_env_runners)
            ]

    def sample(self, num_steps: int = 256) -> List[Dict[str, np.ndarray]]:
        if self.local is not None:
            return [self.local.sample(num_steps)]
        import ray_tpu
        return ray_tpu.get([r.sample.remote(num_steps) for r in self.remotes])

    def sync_weights(self, params) -> None:
        """Broadcast learner params to all runners (reference:
        env_runner_group.py sync_weights)."""
        state = {"params": params}
        if self.local is not None:
            self.local.set_state(state)
            return
        import ray_tpu
        ray_tpu.get([r.set_state.remote(state) for r in self.remotes])

    def aggregate_metrics(self) -> Dict[str, float]:
        if self.local is not None:
            return self.local.metrics()
        import ray_tpu
        all_m = ray_tpu.get([r.metrics.remote() for r in self.remotes])
        rets = [m["episode_return_mean"] for m in all_m
                if not np.isnan(m["episode_return_mean"])]
        lens = [m["episode_len_mean"] for m in all_m
                if not np.isnan(m["episode_len_mean"])]
        return {
            "episode_return_mean": float(np.mean(rets)) if rets else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "num_episodes": int(sum(m["num_episodes"] for m in all_m)),
        }

    def stop(self) -> None:
        import ray_tpu
        for r in self.remotes:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
