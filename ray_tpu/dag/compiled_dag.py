"""CompiledDAG: static plan + per-actor execution loops over shm channels.

Reference: python/ray/dag/compiled_dag_node.py:804 (CompiledDAG — compile
the bound DAG into ExecutableTasks per actor, allocate channels per edge,
run a resident loop on each actor, drive I/O from the driver) and
:2545 (execute).

Differences from per-call actor RPC: the graph is planned once — argument
routing, channel allocation, intra-actor locality — and each ``execute``
only moves payload bytes through single-writer/single-reader channels.
Capacity-1 channels give pipelined backpressure: stage k can work on
iteration i+1 while stage k+1 still holds iteration i.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.exceptions import TaskError
from .channel import FLAG_DATA, FLAG_ERR, FLAG_STOP, ShmChannel

EdgeKey = Tuple[int, int]  # (producer node idx, consumer node idx; -1=driver)


def _dag_actor_loop(instance, plan: Dict[str, Any]) -> int:
    """Resident loop executed on the actor's worker via __ray_call__.

    Each iteration: for each of this actor's steps in topo order, read that
    step's input edges immediately before executing it, then write results
    to out-channels.  Per-step (not up-front) reads matter: a DAG that
    revisits an actor after passing through another (a.f -> b.g -> a.h)
    would deadlock if the loop blocked on the b->a channel before running
    f to feed b.  Errors are propagated as FLAG_ERR payloads instead of
    crashing the pipeline; STOP propagates downstream and ends the loop.
    """
    steps = plan["steps"]
    in_channels: Dict[EdgeKey, ShmChannel] = plan["in_channels"]
    out_channels: Dict[EdgeKey, ShmChannel] = plan["out_channels"]
    # Each in-channel feeds exactly one consumer step (edge keys embed the
    # consumer node idx); dedupe so a channel used in two arg positions of
    # the same step is read once per iteration.
    for step in steps:
        reads: List[EdgeKey] = []
        for kind, payload in list(step["args"]) + list(step["kwargs"].values()):
            if kind == "chan" and payload not in reads:
                reads.append(payload)
        step["reads"] = reads
    iterations = 0
    try:
        while True:
            chan_vals: Dict[EdgeKey, Any] = {}
            chan_errs: Dict[EdgeKey, bytes] = {}
            stop = False
            local_vals: Dict[int, Any] = {}
            local_errs: Dict[int, bytes] = {}
            for step in steps:
                for key in step["reads"]:
                    flag, payload = in_channels[key].read()
                    if flag == FLAG_STOP:
                        stop = True
                    elif flag == FLAG_ERR:
                        chan_errs[key] = payload
                    else:
                        chan_vals[key] = serialization.unpack_payload(payload)
                if stop:
                    break
                node_idx = step["node_idx"]
                if step.get("kind") == "collective":
                    # Broadcast this rank's contribution, read peers',
                    # reduce locally (all writes precede all reads, so
                    # capacity-1 channels cannot deadlock).
                    from .collective import _tree_reduce
                    _, contrib_idx = step["input"]
                    c_err = local_errs.get(contrib_idx)
                    if c_err is not None:
                        for key in step["peer_writes"]:
                            out_channels[key].write(c_err, FLAG_ERR)
                    else:
                        c_payload = serialization.pack_payload(
                            local_vals[contrib_idx])
                        for key in step["peer_writes"]:
                            out_channels[key].write(c_payload, FLAG_DATA)
                    values = [] if c_err is not None else \
                        [local_vals[contrib_idx]]
                    coll_err = c_err
                    for key in step["peer_reads"]:
                        flag, payload = in_channels[key].read()
                        if flag == FLAG_STOP:
                            stop = True
                        elif flag == FLAG_ERR:
                            coll_err = coll_err or payload
                        else:
                            values.append(
                                serialization.unpack_payload(payload))
                    if stop:
                        break
                    if coll_err is not None:
                        local_errs[node_idx] = coll_err
                        for key in step["writes"]:
                            out_channels[key].write(coll_err, FLAG_ERR)
                    else:
                        try:
                            reduced = _tree_reduce(step["op"], values)
                            local_vals[node_idx] = reduced
                            payload = serialization.pack_payload(reduced)
                            for key in step["writes"]:
                                out_channels[key].write(payload, FLAG_DATA)
                        except BaseException as exc:  # noqa: BLE001
                            import traceback
                            e_payload = serialization.pack_payload(
                                TaskError(exc, f"allreduce[{step['op']}]",
                                          traceback.format_exc()))
                            local_errs[node_idx] = e_payload
                            for key in step["writes"]:
                                out_channels[key].write(e_payload, FLAG_ERR)
                    continue
                err: Optional[bytes] = None
                args: List[Any] = []
                kwargs: Dict[str, Any] = {}

                def resolve(spec):
                    nonlocal err
                    kind, payload = spec
                    if kind == "const":
                        return payload
                    if kind == "chan":
                        if payload in chan_errs:
                            err = err or chan_errs[payload]
                            return None
                        return chan_vals[payload]
                    # kind == "local"
                    if payload in local_errs:
                        err = err or local_errs[payload]
                        return None
                    return local_vals[payload]

                for spec in step["args"]:
                    args.append(resolve(spec))
                for k, spec in step["kwargs"].items():
                    kwargs[k] = resolve(spec)
                if err is None:
                    try:
                        method = getattr(instance, step["method"])
                        out = method(*args, **kwargs)
                        local_vals[node_idx] = out
                    except BaseException as exc:  # noqa: BLE001 — forwarded
                        import traceback
                        err = serialization.pack_payload(
                            TaskError(exc, step["method"],
                                      traceback.format_exc()))
                if err is not None:
                    local_errs[node_idx] = err
                    for key in step["writes"]:
                        out_channels[key].write(err, FLAG_ERR)
                else:
                    payload = serialization.pack_payload(local_vals[node_idx])
                    for key in step["writes"]:
                        out_channels[key].write(payload, FLAG_DATA)
            if stop:
                # Teardown drains all executes before sending STOP, so the
                # first read of a fresh iteration is the only place STOP
                # appears — no step has written this iteration yet.
                for chan in out_channels.values():
                    chan.write(b"", FLAG_STOP)
                return iterations
            iterations += 1
    finally:
        for chan in list(in_channels.values()) + list(out_channels.values()):
            chan.close()


class CompiledDAGRef:
    """Future for one compiled execution (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", index: int):
        self._dag = dag
        self._index = index
        self._value: Any = None
        self._fetched = False

    def get(self, timeout: Optional[float] = None):
        if not self._fetched:
            self._value = self._dag._fetch(self._index, timeout)
            self._fetched = True
        if isinstance(self._value, Exception):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, output_node, *, buffer_size_bytes: int = 1 << 20,
                 submit_timeout: float = 30.0):
        from . import (ClassMethodNode, DAGNode, InputAttributeNode,
                       InputNode, MultiOutputNode)
        from .collective import CollectiveOutputNode
        self._buffer = buffer_size_bytes
        self._submit_timeout = submit_timeout
        self._lock = threading.Lock()
        self._torn_down = False
        self._next_execute = 0
        self._next_fetch = 0
        self._fetched: Dict[int, Any] = {}

        # ---- topo order over reachable nodes --------------------------- #
        order: List[Any] = []
        seen: Dict[int, int] = {}
        on_path: set = set()

        def visit(node):
            nid = id(node)
            if nid in seen:
                return
            if nid in on_path:
                raise ValueError("cycle detected in DAG")
            on_path.add(nid)
            for up in node._upstream():
                visit(up)
            on_path.discard(nid)
            seen[nid] = len(order)
            order.append(node)

        visit(output_node)
        idx_of = {id(n): i for i, n in enumerate(order)}

        terminals: List[Any]
        if isinstance(output_node, MultiOutputNode):
            terminals = output_node._outputs
        else:
            terminals = [output_node]
        if len({id(t) for t in terminals}) != len(terminals):
            raise ValueError("duplicate node in MultiOutputNode outputs")
        for t in terminals:
            if not isinstance(t, (ClassMethodNode, CollectiveOutputNode)):
                raise ValueError(
                    "compiled DAG outputs must be actor method calls or "
                    f"collective outputs, got {type(t).__name__}")
        compute_nodes = [n for n in order
                         if isinstance(n, (ClassMethodNode,
                                           CollectiveOutputNode))]
        if not any(isinstance(n, ClassMethodNode) for n in compute_nodes):
            raise ValueError("DAG contains no actor method calls")
        # Every output of a collective group must be part of this DAG:
        # the peer broadcast needs all ranks resident (reference:
        # collective_node.py binds all participants together).
        for n in compute_nodes:
            if isinstance(n, CollectiveOutputNode):
                for out in n._group.outputs:
                    if id(out) not in idx_of:
                        raise ValueError(
                            "all outputs of a collective group must be "
                            "consumed by (or be outputs of) the same "
                            "compiled DAG")
        for n in order:
            if isinstance(n, MultiOutputNode) and n is not output_node:
                raise ValueError("MultiOutputNode must be the DAG output")

        # Every compute node must (transitively) depend on the input so each
        # actor loop is triggered exactly once per execute.
        reaches_input: Dict[int, bool] = {}

        def check_reach(node) -> bool:
            nid = id(node)
            if nid in reaches_input:
                return reaches_input[nid]
            if isinstance(node, (InputNode, InputAttributeNode)):
                reaches_input[nid] = True
                return True
            r = any(check_reach(u) for u in node._upstream())
            reaches_input[nid] = r
            return r

        for n in compute_nodes:
            if not check_reach(n):
                raise ValueError(
                    f"{n!r} does not depend on the InputNode; every compiled "
                    "task needs a per-iteration trigger")

        # ---- plan edges ------------------------------------------------- #
        # (prod_idx, cons_idx) -> ShmChannel for cross-process edges.
        self._channels: Dict[EdgeKey, ShmChannel] = {}
        # input-producing nodes the driver must feed per edge.
        self._input_edges: List[Tuple[EdgeKey, Any]] = []  # (key, node)
        actor_of = {}  # node idx -> actor handle (by actor_id)
        for n in compute_nodes:
            actor_of[idx_of[id(n)]] = n._actor

        plans: Dict[bytes, Dict[str, Any]] = {}  # actor_id bits -> plan

        def plan_for(actor) -> Dict[str, Any]:
            key = actor._actor_id.binary()
            if key not in plans:
                plans[key] = {"actor": actor, "steps": [],
                              "in_channels": {}, "out_channels": {}}
            return plans[key]

        def make_channel(ekey: EdgeKey) -> ShmChannel:
            if ekey not in self._channels:
                self._channels[ekey] = ShmChannel(self._buffer)
            return self._channels[ekey]

        planned_groups: set = set()
        self._peer_keys: set = set()  # collective peer edges; not consumer
        for n in compute_nodes:
            cons_idx = idx_of[id(n)]
            plan = plan_for(n._actor)
            if isinstance(n, CollectiveOutputNode):
                # Peer-to-peer broadcast + local reduce (one step per rank;
                # reference: collective_node.py lowering to NCCL allreduce,
                # here to pairwise shm channels).
                group = n._group
                gid = id(group)
                out_idx = {r: idx_of[id(group.outputs[r])]
                           for r in range(len(group.outputs))}
                if gid not in planned_groups:
                    planned_groups.add(gid)
                    for i in range(len(group.outputs)):
                        for j in range(len(group.outputs)):
                            if i != j:
                                pkey = (out_idx[i], out_idx[j])
                                make_channel(pkey)
                                self._peer_keys.add(pkey)
                rank = n._rank
                contrib = group.inputs[rank]
                peer_writes = []
                peer_reads = []
                for j in range(len(group.outputs)):
                    if j == rank:
                        continue
                    wkey = (out_idx[rank], out_idx[j])
                    rkey = (out_idx[j], out_idx[rank])
                    plan["out_channels"][wkey] = self._channels[wkey]
                    plan["in_channels"][rkey] = self._channels[rkey]
                    peer_writes.append(wkey)
                    peer_reads.append(rkey)
                plan["steps"].append({
                    "kind": "collective", "node_idx": cons_idx,
                    "op": group.op,
                    "input": ("local", idx_of[id(contrib)]),
                    "peer_writes": peer_writes, "peer_reads": peer_reads,
                    "args": [], "kwargs": {}, "writes": [],
                })
                continue
            arg_specs: List[Tuple[str, Any]] = []
            kwarg_specs: Dict[str, Tuple[str, Any]] = {}

            def spec_for(a):
                from . import DAGNode as _DN
                if not isinstance(a, _DN):
                    return ("const", a)
                prod_idx = idx_of[id(a)]
                if isinstance(a, (InputNode, InputAttributeNode)):
                    ekey = (prod_idx, cons_idx)
                    chan = make_channel(ekey)
                    plan["in_channels"][ekey] = chan
                    if all(k != ekey for k, _ in self._input_edges):
                        self._input_edges.append((ekey, a))
                    return ("chan", ekey)
                # producer is a ClassMethodNode
                prod_actor = actor_of[prod_idx]
                if prod_actor._actor_id == n._actor._actor_id:
                    return ("local", prod_idx)
                ekey = (prod_idx, cons_idx)
                chan = make_channel(ekey)
                plan["in_channels"][ekey] = chan
                plan_for(prod_actor)["out_channels"][ekey] = chan
                return ("chan", ekey)

            for a in n._args:
                arg_specs.append(spec_for(a))
            for k, a in n._kwargs.items():
                kwarg_specs[k] = spec_for(a)
            plan["steps"].append({
                "node_idx": cons_idx, "method": n._method,
                "args": arg_specs, "kwargs": kwarg_specs, "writes": [],
            })

        # Producer "writes" lists: fill after all edges are known.  Peer
        # channels are excluded: the collective step writes CONTRIBUTIONS
        # into them itself — treating them as consumer edges would push
        # the reduced value in as well, leaving a stale payload that
        # deadlocks the next iteration's contribution write.
        for ekey in self._channels:
            if ekey in self._peer_keys:
                continue
            prod_idx, cons_idx = ekey
            if prod_idx in actor_of:  # produced by an actor step
                plan = plan_for(actor_of[prod_idx])
                for step in plan["steps"]:
                    if step["node_idx"] == prod_idx and ekey not in step["writes"]:
                        step["writes"].append(ekey)

        # Output edges: terminal -> driver.
        self._output_keys: List[EdgeKey] = []
        for t in terminals:
            t_idx = idx_of[id(t)]
            ekey = (t_idx, -1)
            chan = make_channel(ekey)
            plan = plan_for(t._actor)
            plan["out_channels"][ekey] = chan
            for step in plan["steps"]:
                if step["node_idx"] == t_idx and ekey not in step["writes"]:
                    step["writes"].append(ekey)
            self._output_keys.append(ekey)
        self._multi_output = isinstance(output_node, MultiOutputNode)

        # Steps already appended in topo order (compute_nodes follows
        # `order`). Launch the loops.
        self._loop_refs = []
        for plan in plans.values():
            actor = plan.pop("actor")
            self._loop_refs.append(
                actor.__ray_call__.remote(_dag_actor_loop, plan))

    # ------------------------------------------------------------------ #

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        from . import InputNode
        with self._lock:
            if self._torn_down:
                raise RuntimeError("compiled DAG has been torn down")
            payloads = []
            for ekey, node in self._input_edges:
                if isinstance(node, InputNode):
                    value = node._eval_impl(None, args, kwargs)
                else:
                    value = InputNode.extract(node._key, args, kwargs)
                payloads.append((ekey, serialization.pack_payload(value)))
            # All-or-nothing submission: wait until EVERY input channel is
            # writable before writing ANY, so a saturated pipeline fails
            # without leaving some channels holding this iteration's value
            # and others not (which would silently pair inputs from
            # different execute() calls after a retry).  Writability is
            # monotonic here — the driver under this lock is the only
            # writer — so the post-check writes cannot block.
            import time as _time
            deadline = _time.monotonic() + self._submit_timeout
            try:
                for ekey, _ in payloads:
                    self._channels[ekey].wait_writable(
                        max(0.0, deadline - _time.monotonic()))
            except TimeoutError as e:
                raise RuntimeError(
                    "compiled DAG pipeline is full — call .get() on "
                    "earlier CompiledDAGRefs before submitting more "
                    "executions") from e
            for ekey, payload in payloads:
                self._channels[ekey].write(payload, FLAG_DATA)
            index = self._next_execute
            self._next_execute += 1
        return CompiledDAGRef(self, index)

    def _fetch(self, index: int, timeout: Optional[float]) -> Any:
        with self._lock:
            if index in self._fetched:
                return self._fetched.pop(index)
            if self._torn_down and self._next_fetch > index:
                raise RuntimeError(
                    "compiled DAG was torn down before this result was "
                    "fetched")
            while self._next_fetch <= index:
                self._advance(timeout)
            return self._fetched.pop(index)

    def _check_loops_alive(self) -> None:
        """Surface actor-loop death instead of spinning forever."""
        import ray_tpu
        done, _ = ray_tpu.wait(self._loop_refs,
                               num_returns=len(self._loop_refs), timeout=0)
        if done and not self._torn_down:
            try:
                ray_tpu.get(done)
            except Exception as e:
                raise RuntimeError(
                    f"a compiled DAG actor loop died: {e!r}") from e
            raise RuntimeError(
                "a compiled DAG actor loop exited unexpectedly")

    def _advance(self, timeout: Optional[float]) -> None:
        """Read one full iteration's outputs into ``_fetched``.

        Partially-read outputs are staged in ``_partial`` so a timeout
        midway never desyncs the channels: a retry resumes with the
        channels that were not yet read.  The timeout is a shared deadline
        across all outputs, with liveness checks between bounded waits.
        """
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        if not hasattr(self, "_partial"):
            self._partial = {}
        while len(self._partial) < len(self._output_keys):
            pos = len(self._partial)
            ekey = self._output_keys[pos]
            if deadline is None:
                slice_timeout = 1.0
            else:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"timed out fetching compiled DAG output {pos}")
                slice_timeout = min(1.0, remaining)
            try:
                flag, payload = self._channels[ekey].read(slice_timeout)
            except TimeoutError:
                self._check_loops_alive()
                continue
            self._partial[pos] = (flag, payload)
        results = []
        error: Optional[Exception] = None
        for pos in range(len(self._output_keys)):
            flag, payload = self._partial[pos]
            if flag == FLAG_ERR:
                error = error or serialization.unpack_payload(payload)
                results.append(None)
            elif flag == FLAG_STOP:
                error = error or RuntimeError("DAG torn down")
                results.append(None)
            else:
                results.append(serialization.unpack_payload(payload))
        self._partial = {}
        value: Any = error if error is not None else (
            results if self._multi_output else results[0])
        self._fetched[self._next_fetch] = value
        self._next_fetch += 1

    def teardown(self) -> None:
        import ray_tpu
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
            # Drain unfetched results so STOP can flow through capacity-1
            # channels without blocking on stale payloads.  Drained values
            # stay in _fetched so later ref.get() calls still succeed.
            try:
                while self._next_fetch < self._next_execute:
                    self._advance(timeout=5.0)
            except Exception:
                pass
            for ekey, _node in self._input_edges:
                try:
                    self._channels[ekey].write(b"", FLAG_STOP, timeout=5.0)
                except Exception:
                    pass
        try:
            ray_tpu.get(self._loop_refs, timeout=10.0)
        except Exception:
            pass
        for chan in self._channels.values():
            chan.close()
            chan.unlink()

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown()
        except Exception:
            pass
