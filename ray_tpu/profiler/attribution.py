"""Always-on step attribution: decompose a training step into phases.

``ray_tpu.train.step_phase`` (re-exported from here) marks what each
slice of a step's wall time actually was — waiting on the input
pipeline, host→device transfer, dispatched compute, collectives — by
fencing with ``jax.block_until_ready`` at phase boundaries so XLA's
async dispatch cannot smear one phase's device work into the next::

    with train.step_phase("data_wait"):
        batch = next(it)
    with train.step_phase("h2d"):
        batch = train.fence(place(batch))
    with train.step_phase("compute"):
        state, loss = train.fence(step_fn(state, batch))
    train.report({"loss": float(loss)})

``report()`` pops the accumulated phases, publishes per-phase
``ray_tpu_train_step_phase_seconds{phase}`` observations (rank 0), adds
a derived ``other`` phase for the unattributed remainder, and ships the
dict to the controller — which feeds the goodput tracker's data-wait
idle attribution and the ``Result.step_phases`` summary.

Canonical phase names (free-form strings are accepted but keep tag
cardinality in mind): ``data_wait``, ``h2d``, ``compute``,
``collective``; ``ckpt_block`` and ``other`` are added automatically.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, Optional

DERIVED_PHASES = ("ckpt_block", "other")

_tls = threading.local()


def _phases() -> Dict[str, float]:
    acc = getattr(_tls, "phases", None)
    if acc is None:
        acc = _tls.phases = {}
    return acc


def fence(value: Any) -> Any:
    """Block until every array in ``value`` is computed, then return it
    unchanged — the phase boundary.  A no-op when jax isn't loaded (the
    attribution API stays importable in array-free train fns)."""
    if "jax" in sys.modules:
        try:
            import jax
            jax.block_until_ready(value)
        except Exception:  # noqa: BLE001 — non-array pytrees etc.
            pass
    return value


class step_phase:
    """Context manager charging its wall time to one named phase of the
    current step.  Re-entrant (per-entry state is a stack) and nestable:
    nested time is charged to the INNER phase only, so phases sum to at
    most the step time instead of double counting.

    ``fence_result=x`` (or calling :meth:`fence` inside the block)
    blocks on ``x`` before the phase closes, so asynchronously
    dispatched device work lands inside the phase that launched it.
    """

    __slots__ = ("name", "_fence_result", "_stack")

    def __init__(self, name: str, fence_result: Any = None):
        self.name = name
        self._fence_result = fence_result
        self._stack: list = []

    def fence(self, value: Any) -> Any:
        """Fence inline and return ``value`` (sugar for assignments)."""
        return fence(value)

    def __enter__(self) -> "step_phase":
        self._stack.append({"t0": time.monotonic(), "child_s": 0.0,
                            "parent": getattr(_tls, "open_phase", None)})
        _tls.open_phase = self._stack[-1]
        return self

    def __exit__(self, *exc) -> bool:
        if self._fence_result is not None:
            fence(self._fence_result)
        entry = self._stack.pop()
        dur = max(0.0, time.monotonic() - entry["t0"])
        _tls.open_phase = entry["parent"]
        if entry["parent"] is not None:
            entry["parent"]["child_s"] += dur
        mine = max(0.0, dur - entry["child_s"])
        acc = _phases()
        acc[self.name] = acc.get(self.name, 0.0) + mine
        return False


def pop_phases() -> Dict[str, float]:
    """Return and clear this thread's accumulated phase seconds (called
    by ``train.report`` once per step)."""
    acc = _phases()
    out = dict(acc)
    acc.clear()
    return out


def finalize_step_phases(phases: Dict[str, float], step_s: Optional[float],
                         ckpt_s: float = 0.0) -> Dict[str, float]:
    """Fold checkpoint-blocking time in and derive ``other`` — the slice
    of the step no phase claimed.  ``step_s`` None (first report: no
    prior report to difference against) skips the derivation."""
    out = {k: v for k, v in phases.items() if v > 0.0}
    if ckpt_s > 0.0:
        out["ckpt_block"] = out.get("ckpt_block", 0.0) + ckpt_s
    if step_s is not None and step_s > 0.0:
        attributed = sum(out.values())
        out["other"] = max(0.0, step_s - attributed)
    return out


_last_hbm_mono = 0.0
_hbm_lock = threading.Lock()


def note_hbm_gauges(min_interval_s: float = 1.0) -> None:
    """Refresh the per-device HBM used/peak gauges from jax memory
    stats.  Rate-limited so sub-second report loops don't pay a device
    query per step; silently absent on backends without memory_stats
    (CPU)."""
    global _last_hbm_mono
    now = time.monotonic()
    with _hbm_lock:
        if now - _last_hbm_mono < min_interval_s:
            return
        _last_hbm_mono = now
    from ..util import telemetry
    from .capture import device_memory_stats
    for rec in device_memory_stats():
        tags = {"device": rec["device"]}
        if rec.get("bytes_in_use") is not None:
            telemetry.set_gauge("ray_tpu_train_hbm_used_bytes",
                                float(rec["bytes_in_use"]), tags=tags)
        if rec.get("peak_bytes_in_use") is not None:
            telemetry.set_gauge("ray_tpu_train_hbm_peak_bytes",
                                float(rec["peak_bytes_in_use"]), tags=tags)


def _reset_for_tests() -> None:
    global _last_hbm_mono
    _phases().clear()
    _tls.open_phase = None
    _last_hbm_mono = 0.0
