"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

Absent from the reference (SURVEY §2.4) — built natively.  Each device holds
a sequence shard of all heads; one all-to-all turns that into all tokens of
a head shard, local full-sequence attention runs (flash kernel), and a
second all-to-all restores the sequence-sharded layout.  Cost is two
all-to-alls of activation size vs ring's N ppermutes of K/V — better when
head count >= sp axis and sequences are long enough for the flash kernel to
dominate.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .attention import reference_attention


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None,
                      attn_fn=None):
    """Call inside shard_map: q/k/v [B, H, S_local, D] seq-sharded.

    H must be divisible by the axis size.  GQA note: K/V heads are
    repeated to full H before the swap when Hkv < axis size would make the
    all-to-all split impossible.
    """
    B, H, Sl, D = q.shape
    n = jax.lax.psum(1, axis_name)
    _, Hkv, _, _ = k.shape
    if Hkv % n:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if H % n:
        raise ValueError(f"heads {H} not divisible by axis size {n}")

    def swap(x):  # [B, h, S_local, D] -> [B, h/n, S, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def unswap(x):  # [B, h/n, S, D] -> [B, h, S_local, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = swap(q), swap(k), swap(v)
    fn = attn_fn or reference_attention
    out = fn(qh, kh, vh, causal=causal, scale=scale)
    return unswap(out)


def ulysses_attention_sharded(q, k, v, mesh=None, *, axis_name: str = "sp",
                              causal: bool = True,
                              scale: Optional[float] = None,
                              in_spec=None):
    import jax
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        from ..parallel.mesh import get_global_mesh
        mesh = get_global_mesh()
    spec = in_spec if in_spec is not None else P(None, None, axis_name, None)
    fn = partial(ulysses_attention, axis_name=axis_name, causal=causal,
                 scale=scale)
    if hasattr(jax, "shard_map"):
        wrapped = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                                out_specs=spec, check_vma=False)
    else:  # pre-stable API (jax < 0.6)
        from jax.experimental.shard_map import shard_map as _shard_map
        wrapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_rep=False)
    return wrapped(q, k, v)
