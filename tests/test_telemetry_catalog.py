"""Built-in telemetry: catalog consistency, cross-subsystem smoke run,
goodput accounting under fault injection.

Reference analogs: python/ray/tests/test_metrics_agent.py (built-in metric
catalog exposure) + the MegaScale-style goodput accounting the train
controller implements.
"""

import json
import re
import threading
import time

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, FailureConfig
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import telemetry

_NAME_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")
SUBSYSTEMS = ("serve", "llm", "train", "ckpt", "data", "node", "profiler",
              "internal", "autoscaler", "slice", "sched", "metricsview",
              "alerts", "store", "lock", "jax")


class TestCatalog:
    def test_names_types_descriptions(self):
        assert len(telemetry.CATALOG) >= 15
        seen = {}
        for name, spec in telemetry.CATALOG.items():
            assert _NAME_RE.match(name), f"bad metric name {name!r}"
            assert spec["description"].strip(), f"{name} has no description"
            assert spec["type"] in ("counter", "gauge", "histogram"), name
            subsystem = name.split("_")[2]
            assert subsystem in SUBSYSTEMS, \
                f"{name}: unknown subsystem {subsystem!r}"
            # No two registrations of one name with different types (the
            # dict keying makes same-name/same-catalog impossible; this
            # guards against later PRs re-declaring outside the catalog).
            assert seen.setdefault(name, spec["type"]) == spec["type"]
        assert {n.split("_")[2] for n in telemetry.CATALOG} == set(SUBSYSTEMS)

    def test_instantiation_matches_catalog(self):
        metrics_mod._reset_for_tests()
        for name, spec in telemetry.CATALOG.items():
            inst = telemetry._get(name, spec["type"])
            assert inst.metric_type == spec["type"]
        # Second pass hits the cache / aliasing path without error.
        for name, spec in telemetry.CATALOG.items():
            telemetry._get(name, spec["type"])
        metrics_mod._reset_for_tests()

    def test_unknown_or_mistyped_name_raises(self):
        with pytest.raises(KeyError):
            telemetry.counter("ray_tpu_bogus_total")
        with pytest.raises(TypeError):
            telemetry.counter("ray_tpu_train_goodput_ratio")

    def test_watchdog_diagnostics_series_registered(self):
        """The watchdog's verdict counters follow the catalog naming
        scheme (PR 2 diagnostics series ride the same lint as PR 1's)."""
        for name in ("ray_tpu_train_straggler_total",
                     "ray_tpu_train_hang_total"):
            assert name in telemetry.CATALOG, name
            spec = telemetry.CATALOG[name]
            assert spec["type"] == "counter", name
            assert name.endswith("_total"), name
            assert _NAME_RE.match(name), name
            assert name.split("_")[2] == "train", name
            assert spec["description"].strip()
        # The exception-safe helper records them without raising.
        telemetry.inc("ray_tpu_train_straggler_total", 0.0)
        telemetry.inc("ray_tpu_train_hang_total", 0.0)

    def test_checkpoint_series_registered(self):
        """The distributed-checkpointing subsystem's series are declared
        in the catalog (and only there — RT204 lints call sites)."""
        specs = {
            "ray_tpu_ckpt_save_blocking_seconds": "histogram",
            "ray_tpu_ckpt_write_seconds": "histogram",
            "ray_tpu_ckpt_bytes_total": "counter",
            "ray_tpu_ckpt_inflight": "gauge",
            "ray_tpu_ckpt_restore_seconds": "histogram",
            "ray_tpu_ckpt_replica_restores_total": "counter",
        }
        for name, typ in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert name.split("_")[2] == "ckpt", name
            assert telemetry.CATALOG[name]["description"].strip(), name
        assert telemetry.CATALOG["ray_tpu_ckpt_restore_seconds"][
            "tag_keys"] == ("source",)

    def test_preemption_series_registered(self):
        """The preemption/drain robustness series (node lifecycle +
        train urgent-checkpoint/backoff) are declared in the catalog —
        RT204 lints every call site against it."""
        specs = {
            "ray_tpu_node_preempted_total": ("counter", ()),
            "ray_tpu_node_drain_seconds": ("histogram", ()),
            "ray_tpu_node_draining": ("gauge", ()),
            "ray_tpu_train_urgent_ckpt_total": ("counter", ()),
            "ray_tpu_train_restart_backoff_seconds": ("histogram", ()),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        # Exception-safe helpers record them without raising.
        telemetry.inc("ray_tpu_node_preempted_total", 0.0)
        telemetry.observe("ray_tpu_node_drain_seconds", 0.0)
        telemetry.set_gauge("ray_tpu_node_draining", 0.0)
        telemetry.inc("ray_tpu_train_urgent_ckpt_total", 0.0)
        telemetry.observe("ray_tpu_train_restart_backoff_seconds", 0.0)

    def test_lock_contention_series_registered(self):
        """The lock-contention profiler's sampled wait/hold series are
        declared in the catalog — RT204 lints lockdebug's publish path
        against it."""
        for name in ("ray_tpu_lock_wait_seconds",
                     "ray_tpu_lock_hold_seconds"):
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == "histogram", name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == ("site",)
            assert telemetry.CATALOG[name]["description"].strip(), name
        telemetry.observe("ray_tpu_lock_wait_seconds", 0.0,
                          tags={"site": "test.py:1"})
        telemetry.observe("ray_tpu_lock_hold_seconds", 0.0,
                          tags={"site": "test.py:1"})

    def test_disagg_admission_series_registered(self):
        """The disaggregated-serving / admission-control series (PR 6)
        are declared in the catalog: router queue depth, shed counts by
        reason, KV-transfer bytes/latency, chunked-prefill chunks, and
        the serve handle-path shed counter."""
        specs = {
            "ray_tpu_llm_admission_queue_depth": ("gauge", ("class",)),
            "ray_tpu_llm_shed_total": ("counter", ("reason",)),
            "ray_tpu_llm_kv_transfer_bytes_total": ("counter", ()),
            "ray_tpu_llm_kv_transfer_seconds": ("histogram", ("op",)),
            "ray_tpu_llm_prefill_chunks_total": ("counter", ()),
            "ray_tpu_serve_shed_total": ("counter", ("deployment",)),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        # The exception-safe helpers record them without raising.
        telemetry.inc("ray_tpu_llm_shed_total", 0.0,
                      tags={"reason": "queue_full"})
        telemetry.set_gauge("ray_tpu_llm_admission_queue_depth", 0.0,
                            tags={"class": "default"})
        telemetry.observe("ray_tpu_llm_kv_transfer_seconds", 0.0,
                          tags={"op": "export"})

    def test_fleet_series_registered(self):
        """The serving-fleet series (llm.fleet: replica-count gauge,
        prefix-affinity routing outcomes, imbalance rebalances, and
        autoscaler replica add/remove) are declared in the catalog."""
        specs = {
            "ray_tpu_serve_replica_count": ("gauge", ("fleet",)),
            "ray_tpu_serve_prefix_hit_total": ("counter", ("outcome",)),
            "ray_tpu_serve_rebalance_total": ("counter", ()),
            "ray_tpu_serve_replica_scale_total": ("counter",
                                                  ("direction",)),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        telemetry.set_gauge("ray_tpu_serve_replica_count", 0.0,
                            tags={"fleet": "t"})
        telemetry.inc("ray_tpu_serve_prefix_hit_total", 0.0,
                      tags={"outcome": "full"})
        telemetry.inc("ray_tpu_serve_rebalance_total", 0.0)
        telemetry.inc("ray_tpu_serve_replica_scale_total", 0.0,
                      tags={"direction": "up"})

    def test_mesh_series_registered(self):
        """The mesh-runtime series (train/mesh: live axis sizes,
        per-process parameter shard bytes, reshape events) are declared
        in the catalog — RT204 lints every call site against it."""
        specs = {
            "ray_tpu_train_mesh_axis_size": ("gauge", ("axis",)),
            "ray_tpu_train_param_shard_bytes": ("gauge", ()),
            "ray_tpu_train_mesh_reshapes_total": ("counter", ()),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
            assert name.split("_")[2] == "train", name
        # The exception-safe helpers record them without raising.
        telemetry.set_gauge("ray_tpu_train_mesh_axis_size", 8.0,
                            tags={"axis": "fsdp"})
        telemetry.set_gauge("ray_tpu_train_param_shard_bytes", 0.0)
        telemetry.inc("ray_tpu_train_mesh_reshapes_total", 0.0)

    def test_spotfleet_series_registered(self):
        """The goodput-driven autoscaling / spot-fleet elasticity series
        (pre-buy, goodput scale events, upsize, slice drains, pending
        pre-buy gauge) are declared in the catalog — RT204 lints every
        call site against it."""
        specs = {
            "ray_tpu_autoscaler_prebuy_total": ("counter", ()),
            "ray_tpu_autoscaler_goodput_scale_events_total":
                ("counter", ("direction",)),
            "ray_tpu_autoscaler_pending_prebuys": ("gauge", ()),
            "ray_tpu_train_upsize_total": ("counter", ()),
            "ray_tpu_slice_drains_total": ("counter", ()),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        # The exception-safe helpers record them without raising.
        telemetry.inc("ray_tpu_autoscaler_prebuy_total", 0.0)
        telemetry.inc("ray_tpu_autoscaler_goodput_scale_events_total",
                      0.0, tags={"direction": "up"})
        telemetry.set_gauge("ray_tpu_autoscaler_pending_prebuys", 0.0)
        telemetry.inc("ray_tpu_train_upsize_total", 0.0)
        telemetry.inc("ray_tpu_slice_drains_total", 0.0)

    def test_sched_series_registered(self):
        """The control-plane telescope's series (decision counts by
        kind, lifecycle stage waits, placement attempts, PG two-phase
        commit latency, queue depths) are declared in the catalog —
        RT204 lints every call site against it."""
        specs = {
            "ray_tpu_sched_decisions_total": ("counter", ("kind",)),
            "ray_tpu_sched_stage_wait_seconds": ("histogram", ("stage",)),
            "ray_tpu_sched_placement_attempts": ("histogram", ()),
            "ray_tpu_sched_pg_commit_seconds": ("histogram", ()),
            "ray_tpu_sched_queue_depth": ("gauge", ("queue",)),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
            assert name.split("_")[2] == "sched", name
        # The exception-safe helpers record them without raising.
        telemetry.inc("ray_tpu_sched_decisions_total", 0.0,
                      tags={"kind": "inline"})
        telemetry.observe("ray_tpu_sched_stage_wait_seconds", 0.0,
                          tags={"stage": "queue"})
        telemetry.observe_many("ray_tpu_sched_placement_attempts", [1.0])
        telemetry.set_gauge("ray_tpu_sched_queue_depth", 0.0,
                            tags={"queue": "ready"})

    def test_metricsview_series_registered(self):
        """The time-series backplane's own health series (store ingest /
        drop accounting) and the SLO burn-rate engine's alert series are
        declared in the catalog — RT204 lints every call site."""
        specs = {
            "ray_tpu_metricsview_points_total": ("counter", ()),
            "ray_tpu_metricsview_dropped_total": ("counter", ()),
            "ray_tpu_alerts_firing": ("gauge", ()),
            "ray_tpu_alerts_transitions_total": ("counter", ("state",)),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        # The exception-safe helpers record them without raising.
        telemetry.inc("ray_tpu_metricsview_points_total", 0.0)
        telemetry.inc("ray_tpu_metricsview_dropped_total", 0.0)
        telemetry.set_gauge("ray_tpu_alerts_firing", 0.0)
        telemetry.inc("ray_tpu_alerts_transitions_total", 0.0,
                      tags={"state": "pending"})

    def test_store_series_registered(self):
        """The data-plane telescope's series (object-store occupancy
        gauges, lifecycle/spill op counters, spill-GC reclaimed bytes,
        cross-node transfer bytes + latency) are declared in the
        catalog — RT204 lints every call site against it."""
        specs = {
            "ray_tpu_store_used_bytes": ("gauge", ("node",)),
            "ray_tpu_store_capacity_bytes": ("gauge", ("node",)),
            "ray_tpu_store_pinned_bytes": ("gauge", ("node",)),
            "ray_tpu_store_spilled_bytes": ("gauge", ("node",)),
            "ray_tpu_store_objects": ("gauge", ("node",)),
            "ray_tpu_store_ops_total": ("counter", ("op",)),
            "ray_tpu_store_spill_ops_total": ("counter", ("op",)),
            "ray_tpu_store_spill_reclaimed_bytes_total": ("counter", ()),
            "ray_tpu_store_transfer_bytes_total": ("counter",
                                                   ("direction",)),
            "ray_tpu_store_transfer_seconds": ("histogram", ("op",)),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
            assert name.split("_")[2] == "store", name
        # The exception-safe helpers record them without raising.
        telemetry.set_gauge("ray_tpu_store_used_bytes", 0.0,
                            tags={"node": "smoke"})
        telemetry.inc("ray_tpu_store_ops_total", 0.0, tags={"op": "get"})
        telemetry.inc("ray_tpu_store_transfer_bytes_total", 0.0,
                      tags={"direction": "pull"})
        telemetry.observe("ray_tpu_store_transfer_seconds", 0.0,
                          tags={"op": "pull"})

    def test_profiler_series_registered(self):
        """The profiler subsystem's series (PR 10: step-phase
        attribution, HBM gauges, compile accounting, capture counter)
        are declared in the catalog — RT204 lints every call site."""
        specs = {
            "ray_tpu_train_step_phase_seconds": ("histogram", ("phase",)),
            "ray_tpu_train_hbm_used_bytes": ("gauge", ("device",)),
            "ray_tpu_train_hbm_peak_bytes": ("gauge", ("device",)),
            "ray_tpu_profiler_compile_total": ("counter", ("fn",)),
            "ray_tpu_profiler_compile_seconds": ("histogram", ("fn",)),
            "ray_tpu_profiler_recompiles_total": ("counter", ("fn",)),
            "ray_tpu_profiler_captures_total": ("counter", ()),
        }
        for name, (typ, tags) in specs.items():
            assert name in telemetry.CATALOG, name
            assert telemetry.CATALOG[name]["type"] == typ, name
            assert tuple(telemetry.CATALOG[name]["tag_keys"]) == tags
            assert telemetry.CATALOG[name]["description"].strip(), name
        # The exception-safe helpers record them without raising.
        telemetry.observe("ray_tpu_train_step_phase_seconds", 0.0,
                          tags={"phase": "data_wait"})
        telemetry.inc("ray_tpu_profiler_compile_total", 0.0,
                      tags={"fn": "smoke"})
        telemetry.inc("ray_tpu_profiler_captures_total", 0.0)


def _base_series(prom_text):
    """Distinct catalog-level metric names present in an exposition."""
    names = set()
    for line in prom_text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample = line.split("{")[0].split(" ")[0]
        for suffix in ("_bucket", "_sum", "_count"):
            if sample.endswith(suffix) and \
                    sample[: -len(suffix)] in telemetry.CATALOG:
                sample = sample[: -len(suffix)]
        if sample in telemetry.CATALOG:
            names.add(sample)
    return names


def _smoke_train_fn(config):
    import time as _t

    import numpy as np

    import ray_tpu.train as train
    w = np.zeros((4, 4), np.float32)
    for i in range(3):
        _t.sleep(0.05)
        # ckpt subsystem rides the same smoke: an async sharded save per
        # step exercises save-blocking/write/bytes/inflight series.
        train.save_checkpoint({"w": w + i, "step": i})
        train.report({"loss": 1.0 / (i + 1), "tokens": 64})


@serve.deployment(name="telemetry_echo")
class _Echo:
    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
    def batched(self, items):
        return items

    def __call__(self, body):
        return self.batched(body)


_LLM_CFG_KW = dict(vocab_size=128, hidden=32, layers=2, heads=4, kv_heads=2,
                   head_dim=8, mlp_dim=64, max_seq_len=128,
                   attention_impl="reference", remat=False)


class TestSmokeAllSubsystems:
    def test_metrics_span_all_subsystems(self, ray_start_isolated,
                                          tmp_path):
        metrics_mod._reset_for_tests()

        # -- train: one fit() on the CPU backend -------------------------
        result = JaxTrainer(
            _smoke_train_fn, train_loop_config={},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="telemetry_smoke",
                                 storage_path=str(tmp_path)),
        ).fit()
        assert result.error is None
        assert result.goodput is not None
        assert 0.0 < result.goodput["goodput_ratio"] <= 1.0

        # -- serve: one deployment handling >= 10 requests ----------------
        handle = serve.run(_Echo.bind())
        for i in range(10):
            out = ray_tpu.get(handle.remote({"i": i}), timeout=60)
            assert out == {"i": i}

        # -- llm: one generate() through the engine -----------------------
        from ray_tpu.llm import InferenceEngine, SamplingParams
        from ray_tpu.models import LlamaConfig
        from ray_tpu.models.llama import init_params
        cfg = LlamaConfig(dtype=jnp.float32, **_LLM_CFG_KW)
        params = init_params(cfg, jax.random.key(0))
        eng = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,))
        toks = eng.generate([[3, 17, 92, 5, 41]],
                            SamplingParams(max_tokens=8))
        assert len(toks[0]) == 8

        # -- profiler: tracked-jit compile accounting ---------------------
        from ray_tpu import profiler
        tracked = profiler.track(jax.jit(lambda x: x + 1),
                                 name="telemetry_smoke_inc")
        tracked(jnp.ones((4,), jnp.float32))

        # -- lock: the contention profiler publishes on a double 1/8
        # sample (hold timing every 8th acquire, telemetry every 8th
        # sampled hold), so 64 acquire/release pairs on a lock created
        # under install_profile() deterministically lands one
        # observation on each ray_tpu_lock_* series.
        from ray_tpu.devtools import lockdebug
        lockdebug.install_profile()
        try:
            lk = threading.Lock()
            for _ in range(64):
                with lk:
                    pass
        finally:
            lockdebug.uninstall_profile()

        # -- jax: the host-sync tripwire publishes on the FIRST sync of a
        # site (then every 64th), so one forced device->host coercion
        # under install() deterministically lands both ray_tpu_jax_*
        # series.
        from ray_tpu.devtools import syncdebug
        syncdebug.install()
        try:
            float(jnp.sum(jnp.arange(8.0)))
        finally:
            syncdebug.uninstall()
            syncdebug.clear()

        # -- data: a small pipeline through the streaming executor --------
        import ray_tpu.data as rdata
        ds = rdata.from_items([{"x": float(i)} for i in range(64)],
                              parallelism=4)
        rows = ds.map(lambda r: {"x": r["x"] * 2}).take_all()
        assert len(rows) == 64

        # -- node: a drain/undrain round-trip (preemption signal plane) --
        from ray_tpu._private.api import _control
        node_hex = _control("nodes")[0]["node_id"]
        assert _control("drain_node", node_hex, 30.0, "smoke") is True
        assert _control("undrain_node", node_hex) is True

        # -- autoscaler + slice: a pre-buy decision through the real
        # policy path (counters book only EXECUTED buys, so the
        # subsystem series land via the pending gauge) + the
        # slice-drain counter the SlicePlacementGroup drain path bumps.
        from ray_tpu.autoscaler import (GoodputAutoscalePolicy,
                                        GoodputPolicyConfig)
        pol = GoodputAutoscalePolicy(GoodputPolicyConfig(
            default_node_type="smoke"))
        assert len(pol.decide([("node-x", None)], pending=0)) == 1
        telemetry.set_gauge("ray_tpu_autoscaler_pending_prebuys", 0.0)
        telemetry.inc("ray_tpu_slice_drains_total")

        # -- sched: the run above placed real tasks through the
        # instrumented scheduler; force the rate-limited publisher so
        # the decision counters / queue gauges land on this scrape,
        # and check the telescope saw the placements.
        from ray_tpu.util import state as rstate
        sched_stats = rstate.sched_stats()
        assert sched_stats["decisions"]["total"] > 0
        assert sched_stats["events"]["num_events"] > 0

        # -- metricsview + alerts: a tiny accounted store pays the
        # ingest/eviction counters deterministically, and one objective
        # walks the full pending -> firing -> resolved -> ok cycle on
        # logical time so the alert gauge + transition counter land on
        # this scrape (the live head store also accounts, but its
        # cadence is wall-clock).
        from ray_tpu.metricsview import SeriesStore, SloEngine, SloObjective
        store = SeriesStore(interval_s=1.0, max_points=2, account=True)
        for i in range(4):  # ring of 2: later appends evict -> dropped
            store.append("smoke_gauge", {}, "gauge", float(i), float(i))
        eng = SloEngine(store)
        eng.set_objectives([SloObjective(
            name="smoke", metric="smoke_gauge", agg="last", op="<",
            threshold=0.5, fast_window_s=2.0, slow_window_s=4.0,
            cooldown_s=0.0)])
        eng.evaluate(now=3.0)   # breach -> pending
        eng.evaluate(now=3.5)   # slow window confirms -> firing
        store.append("smoke_gauge", {}, "gauge", 0.0, 10.0)
        eng.evaluate(now=10.0)  # recovered -> resolved
        eng.evaluate(now=11.0)  # cooldown elapsed -> ok
        assert eng.status(now=11.0)["objectives"][0]["state"] == "ok"
        assert [t["to"] for t in eng.status(now=11.0)["transitions"]] == \
            ["pending", "firing", "resolved", "ok"]

        # -- internal: one accounted swallowed error ----------------------
        telemetry.note_swallowed("test.smoke", RuntimeError("boom"))

        # Worker-side metrics flush deterministically at task completion,
        # but serve latency lands from a watcher thread: poll briefly.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            series = _base_series(metrics_mod.prometheus_text())
            if len(series) >= 15 and all(
                    any(s.startswith(f"ray_tpu_{sub}_") for s in series)
                    for sub in SUBSYSTEMS):
                break
            time.sleep(0.2)
        series = _base_series(metrics_mod.prometheus_text())
        missing = {sub for sub in SUBSYSTEMS
                   if not any(s.startswith(f"ray_tpu_{sub}_")
                              for s in series)}
        assert not missing, f"no series for {missing}; got {sorted(series)}"
        assert len(series) >= 15, sorted(series)

        # Timeline carries engine-step and train-step profile spans.
        trace = json.loads(ray_tpu.timeline())
        names = {e["name"] for e in trace}
        assert "engine_step" in names, sorted(names)
        assert "engine_prefill" in names
        assert "train_step" in names
        assert "train_fit" in names

        # Dashboard summary shape (no HTTP server needed: same code path
        # the /api/metrics/summary endpoint serves).
        summary = telemetry.summary()
        assert set(SUBSYSTEMS) <= set(summary["subsystems"])
        assert summary["goodput"] is not None
        serve.shutdown()


def _goodput_sleep_fn(config):
    import os
    import time as _t

    import ray_tpu.train as train
    _t.sleep(0.3)
    train.report({"loss": 1.0, "tokens": 32})
    marker = config.get("die_marker")
    if marker and not os.path.exists(marker):
        open(marker, "w").close()
        raise RuntimeError("injected train worker failure")
    _t.sleep(0.3)
    train.report({"loss": 0.5, "tokens": 32})


class TestGoodputAccounting:
    def test_ratio_drops_under_fault_injection(self, ray_start_isolated,
                                               tmp_path):
        metrics_mod._reset_for_tests()
        clean = JaxTrainer(
            _goodput_sleep_fn, train_loop_config={},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="goodput_clean",
                                 storage_path=str(tmp_path)),
        ).fit()
        assert clean.error is None
        assert 0.0 < clean.goodput["goodput_ratio"] <= 1.0

        faulty = JaxTrainer(
            _goodput_sleep_fn,
            train_loop_config={"die_marker": str(tmp_path / "died_once")},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="goodput_faulty",
                                 storage_path=str(tmp_path),
                                 failure_config=FailureConfig(
                                     max_failures=1)),
        ).fit()
        assert faulty.error is None
        assert faulty.num_failures == 1
        g = faulty.goodput
        assert 0.0 < g["goodput_ratio"] <= 1.0
        # The kill/restart shows up as restart + lost phases, and the
        # ratio drops measurably vs the clean run.
        assert g["phases_s"].get("restart", 0.0) > 0.0
        assert g["phases_s"].get("lost", 0.0) > 0.0
        assert g["goodput_ratio"] < clean.goodput["goodput_ratio"]
        # The restart also shows on the built-in counter.
        text = metrics_mod.prometheus_text()
        assert "ray_tpu_train_worker_restarts_total 1.0" in text
