"""ray_tpu.metricsview: metrics history, windowed queries, SLO alerts.

The head keeps ONE bounded time-series store (``SeriesStore``) fed by
piggybacking on the worker metrics flush path — every batched
``metrics_push`` control frame (and every query) gives the store a
chance to fold the merged cluster snapshot into per-series rings, rate
limited to its downsample interval.  No second reporting loop, no
scraper process.  On top of the store:

* ``query(name, window_s, agg, tags)`` — windowed aggregates
  (``rate | delta | avg | min | max | last | pNN``), surfaced as
  ``state.metrics_query()``, ``ray-tpu metrics query/history``,
  dashboard ``GET /api/metrics/history`` and job-server
  ``GET /api/cluster/metrics/query``.
* ``SloEngine`` — declarative ``SloObjective`` targets with fast+slow
  dual-window burn rates firing pending→firing→resolved transitions
  into the export-event stream (see slo.py).

Knobs (Config): ``metricsview_interval_s``, ``metricsview_max_points``,
``metricsview_max_series``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .query import AGGS, parse_quantile, validate_agg  # noqa: F401
from .slo import AlertState, SloEngine, SloObjective  # noqa: F401
from .store import SeriesStore, points_from_aggregate  # noqa: F401

__all__ = ["SeriesStore", "MetricsView", "SloEngine", "SloObjective",
           "AlertState", "AGGS", "parse_quantile", "validate_agg",
           "parse_tag_args"]


class MetricsView:
    """The head's store + SLO engine, wired to the flush path.

    ``on_push()`` is called from the ``metrics_push`` control verb after
    each worker flush lands; ``refresh()`` re-aggregates the cluster
    snapshot into the store at most once per downsample interval (a
    no-op costs one monotonic read), then runs one SLO evaluation pass —
    alert cadence tracks ingest cadence by construction.
    """

    def __init__(self, event_sink: Optional[Callable] = None,
                 interval_s: Optional[float] = None,
                 max_points: Optional[int] = None,
                 max_series: Optional[int] = None):
        from ray_tpu._private.config import Config
        self.store = SeriesStore(
            interval_s=interval_s if interval_s is not None
            else Config.get("metricsview_interval_s"),
            max_points=max_points if max_points is not None
            else Config.get("metricsview_max_points"),
            max_series=max_series if max_series is not None
            else Config.get("metricsview_max_series"),
            account=True)
        self.slo = SloEngine(self.store, event_sink=event_sink)
        self._ingest_lock = threading.Lock()
        self._last_ingest: Optional[float] = None

    # -- ingest ------------------------------------------------------------

    def on_push(self) -> None:
        """Flush-path hook (one batched push per worker flush)."""
        self.refresh()

    def refresh(self, force: bool = False,
                now: Optional[float] = None) -> bool:
        """Fold the merged cluster snapshot into the store (throttled to
        the downsample interval unless ``force``); returns whether an
        ingest pass actually ran."""
        now = time.monotonic() if now is None else now
        with self._ingest_lock:
            if not force and self._last_ingest is not None and \
                    now - self._last_ingest < self.store.interval_s:
                return False
            self._last_ingest = now
        from ray_tpu.util import metrics, telemetry
        try:
            by_name, acc = metrics._aggregate_snapshots()
            self.store.ingest(points_from_aggregate(by_name, acc), now)
            self.slo.evaluate(now)
        except Exception as e:  # ingest must never break the flush path
            telemetry.note_swallowed("metricsview.refresh", e)
        return True

    # -- reads (each forces freshness first) -------------------------------

    def query(self, name: str, window_s: float = 60.0, agg: str = "avg",
              tags: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        if not validate_agg(agg):
            raise ValueError(
                f"unknown agg {agg!r}: expected one of {AGGS} or pNN")
        self.refresh()
        return self.store.query(name, window_s, agg, tags=tags)

    def history(self, name: str, window_s: float = 300.0,
                tags: Optional[Dict[str, str]] = None,
                max_points: int = 240) -> Dict[str, Any]:
        self.refresh()
        return self.store.history(name, window_s, tags=tags,
                                  max_points=max_points)

    def alerts(self, recent: int = 50) -> Dict[str, Any]:
        self.refresh()
        return self.slo.status(recent=recent)

    def set_objectives(self, objectives: List) -> int:
        n = self.slo.set_objectives(objectives)
        self.refresh(force=True)
        return n

    # -- forensics ---------------------------------------------------------

    def bundle_snapshot(self, window_s: float = 300.0,
                        max_series: int = 64,
                        max_points: int = 120) -> Dict[str, Any]:
        """Recent history for flight-recorder bundles: every known series
        (capped), newest points first trimmed to ``max_points`` each."""
        names = self.store.series_names()[:max_series]
        return {"stats": self.store.stats(),
                "window_s": window_s,
                "series": {n: self.store.history(
                    n, window_s, max_points=max_points)["series"]
                    for n in names}}


def parse_tag_args(pairs) -> Optional[Dict[str, str]]:
    """CLI helper: ``("k=v", ...)`` -> tags dict (None when empty)."""
    tags: Dict[str, str] = {}
    for raw in pairs or ():
        if "=" not in raw:
            raise ValueError(f"expected key=value, got {raw!r}")
        k, _sep, v = raw.partition("=")
        tags[k.strip()] = v.strip()
    return tags or None
