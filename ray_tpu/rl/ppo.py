"""PPO: clipped-surrogate policy optimization with GAE.

Reference: rllib/algorithms/ppo/ppo.py:365 (PPOConfig) / :391
(training_step: sample from env runners -> learner group update ->
sync weights) and ppo_learner losses — expressed as a pure JAX loss jitted
by JaxLearner.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .learner import JaxLearner, LearnerGroup
from .rl_module import DiscretePolicyModule


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                terminateds: np.ndarray, last_values: np.ndarray,
                gamma: float, lam: float,
                bootstrap_values: np.ndarray = None):
    """Generalized Advantage Estimation over time-major [T, N] rollouts.

    ``dones`` marks episode boundaries (no GAE chaining across them).  The
    per-step bootstrap value is:
      * 0 on terminated steps (the future is worth nothing);
      * ``bootstrap_values[t]`` = V(final_obs) on truncated steps — NOT the
        next buffer row, which after auto-reset holds the next episode's
        reset state;
      * V(s_{t+1}) (``values[t+1]`` / ``last_values`` at the end) otherwise.
    """
    T, N = rewards.shape
    if bootstrap_values is None:
        bootstrap_values = np.zeros((T, N), np.float32)
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_value = last_values
    for t in reversed(range(T)):
        done = dones[t].astype(np.float32)
        term = terminateds[t].astype(np.float32)
        boundary_value = (1.0 - term) * bootstrap_values[t]
        nv = (1.0 - done) * next_value + done * boundary_value
        delta = rewards[t] + gamma * nv - values[t]
        last_gae = delta + gamma * lam * (1.0 - done) * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


def ppo_loss(module: DiscretePolicyModule, params, batch):
    import jax.numpy as jnp
    import jax

    out = module.forward_train(params, batch["obs"])
    logits = out["action_logits"]
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    ratio = jnp.exp(logp - batch["logp_old"])
    adv = batch["advantages"]
    clip = batch["clip_param"][0]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    policy_loss = -jnp.mean(surrogate)
    value_loss = jnp.mean((out["value"] - batch["value_targets"]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    vf_coeff = batch["vf_coeff"][0]
    ent_coeff = batch["ent_coeff"][0]
    total = policy_loss + vf_coeff * value_loss - ent_coeff * entropy
    return total, {"policy_loss": policy_loss, "vf_loss": value_loss,
                   "entropy": entropy,
                   "kl": jnp.mean(batch["logp_old"] - logp)}


def ppo_loss_recurrent(module, params, batch):
    """PPO loss over SEQUENCE minibatches for stateful modules: the
    module replays each env's whole rollout window from its recorded
    start state, resetting at in-window episode boundaries (reference:
    rllib recurrent PPO with sequence batching)."""
    import jax
    import jax.numpy as jnp

    out = module.forward_train(params, batch["obs"], batch["state_in"],
                               batch["resets"])
    logits = out["action_logits"]                  # [B, T, A]
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32),
        axis=-1)[..., 0]                           # [B, T]
    ratio = jnp.exp(logp - batch["logp_old"])
    adv = batch["advantages"]
    clip = batch["clip_param"][0]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    policy_loss = -jnp.mean(surrogate)
    value_loss = jnp.mean((out["value"] - batch["value_targets"]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = policy_loss + batch["vf_coeff"][0] * value_loss \
        - batch["ent_coeff"][0] * entropy
    return total, {"policy_loss": policy_loss, "vf_loss": value_loss,
                   "entropy": entropy,
                   "kl": jnp.mean(batch["logp_old"] - logp)}


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self.clip_param = 0.2
        self.lambda_ = 0.95
        self.num_epochs = 4
        self.minibatch_size = 128
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01

    def training(self, *, clip_param=None, lambda_=None, num_epochs=None,
                 minibatch_size=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kw) -> "PPOConfig":
        super().training(**kw)
        if clip_param is not None:
            self.clip_param = clip_param
        if lambda_ is not None:
            self.lambda_ = lambda_
        if num_epochs is not None:
            self.num_epochs = num_epochs
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        spec = config.module_spec()
        lr, seed = config.lr, config.seed
        module_factory = config.module_factory

        def factory():
            module = module_factory() if module_factory \
                else DiscretePolicyModule(spec)
            loss = ppo_loss_recurrent \
                if hasattr(module, "initial_state") else ppo_loss
            return JaxLearner(module, loss, learning_rate=lr, seed=seed)

        self.learner_group = LearnerGroup(
            factory, num_learners=config.num_learners)
        self._rng = np.random.default_rng(config.seed)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_ref())

    def training_step(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.config
        rollouts = self.env_runner_group.sample(cfg.rollout_fragment_length)
        if "state_in" in rollouts[0]:
            return self._training_step_recurrent(cfg, rollouts)

        flat: Dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "logp_old",
                                  "advantages", "value_targets")}
        for ro in rollouts:
            adv, ret = compute_gae(ro["rewards"], ro["values"], ro["dones"],
                                   ro["terminateds"], ro["last_values"],
                                   cfg.gamma, cfg.lambda_,
                                   ro.get("bootstrap_values"))
            T, N = ro["rewards"].shape
            flat["obs"].append(ro["obs"].reshape(T * N, -1))
            flat["actions"].append(ro["actions"].reshape(-1))
            flat["logp_old"].append(ro["logp"].reshape(-1))
            flat["advantages"].append(adv.reshape(-1))
            flat["value_targets"].append(ret.reshape(-1))
        batch = {k: np.concatenate(v) for k, v in flat.items()}
        adv = batch["advantages"]
        batch["advantages"] = ((adv - adv.mean())
                               / (adv.std() + 1e-8)).astype(np.float32)

        n = len(batch["actions"])
        consts = {
            "clip_param": np.array([cfg.clip_param], np.float32),
            "vf_coeff": np.array([cfg.vf_loss_coeff], np.float32),
            "ent_coeff": np.array([cfg.entropy_coeff], np.float32),
        }
        metrics: Dict[str, float] = {}
        mb = min(cfg.minibatch_size, n)
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for s in range(0, n - mb + 1, mb):
                idx = perm[s:s + mb]
                minibatch = {k: v[idx] for k, v in batch.items()}
                minibatch.update(consts)
                metrics = self.learner_group.update(minibatch)
        # Ref-based broadcast: runners pull the new weights from the object
        # store; the driver never materializes the pytree.
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_ref())
        return {"learner": metrics,
                "num_env_steps_sampled": n}

    def _training_step_recurrent(self, cfg: "PPOConfig",
                                 rollouts) -> Dict[str, Any]:
        """Sequence batching for stateful modules: rows are whole
        per-env rollout windows ([B, T] arrays, never shuffled across
        time); the learner replays each from its recorded start state
        with resets at in-window episode boundaries (reference: rllib
        recurrent PPO sequence batching)."""
        seq: Dict[str, list] = {k: [] for k in
                                ("obs", "actions", "logp_old",
                                 "advantages", "value_targets",
                                 "state_in", "resets")}
        for ro in rollouts:
            adv, ret = compute_gae(ro["rewards"], ro["values"], ro["dones"],
                                   ro["terminateds"], ro["last_values"],
                                   cfg.gamma, cfg.lambda_,
                                   ro.get("bootstrap_values"))
            dones = np.swapaxes(ro["dones"], 0, 1)         # [N, T]
            resets = np.zeros_like(dones)
            resets[:, 1:] = dones[:, :-1]
            seq["obs"].append(np.swapaxes(ro["obs"], 0, 1))
            seq["actions"].append(np.swapaxes(ro["actions"], 0, 1))
            seq["logp_old"].append(np.swapaxes(ro["logp"], 0, 1))
            seq["advantages"].append(np.swapaxes(adv, 0, 1))
            seq["value_targets"].append(np.swapaxes(ret, 0, 1))
            seq["state_in"].append(ro["state_in"])
            seq["resets"].append(resets)
        batch = {k: np.concatenate(v) for k, v in seq.items()}
        adv = batch["advantages"]
        batch["advantages"] = ((adv - adv.mean())
                               / (adv.std() + 1e-8)).astype(np.float32)
        n_rows, T = batch["actions"].shape
        consts = {
            "clip_param": np.array([cfg.clip_param], np.float32),
            "vf_coeff": np.array([cfg.vf_loss_coeff], np.float32),
            "ent_coeff": np.array([cfg.entropy_coeff], np.float32),
        }
        metrics: Dict[str, float] = {}
        mb_rows = max(1, min(n_rows, cfg.minibatch_size // max(T, 1)))
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n_rows)
            for s in range(0, n_rows - mb_rows + 1, mb_rows):
                idx = perm[s:s + mb_rows]
                minibatch = {k: v[idx] for k, v in batch.items()}
                minibatch.update(consts)
                metrics = self.learner_group.update(minibatch)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_ref())
        return {"learner": metrics,
                "num_env_steps_sampled": n_rows * T}

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params) -> None:
        self.learner_group.set_weights(params)

    def stop(self) -> None:
        super().stop()
        self.learner_group.stop()
