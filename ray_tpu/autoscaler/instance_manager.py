"""Declarative instance manager: desired state in, provider actions out.

Reference: the v2 autoscaler's InstanceManager
(python/ray/autoscaler/v2/instance_manager/instance_manager.py) and its
reconciler (v2/instance_manager/reconciler.py) — instances move through
an explicit lifecycle FSM, every transition is persisted with a version,
and the reconciler converges ACTUAL (what the cloud + the cluster
report) toward DESIRED (what the scheduler wants), never trusting its
own memory of in-flight work.  Launches are idempotent by request id, so
a crashed-and-restarted reconciler re-issues the same request instead of
double-buying a TPU slice.

TPU-first sizing: the provider ABC models GKE's QueuedResources flow —
you *request* a slice (maybe multi-host), the request sits QUEUED until
the fabric has capacity, then every host of the slice comes up together
and each host's node server joins the head.  A slice is therefore the
atomic unit of request/terminate, with per-host bind tracking.

Lifecycle:

    REQUESTED     reconciler asked the provider for the instance
    PROVISIONING  provider acknowledged; resource not yet running
    RUNNING       cloud reports the VM/host up; node not yet joined
    JOINED        a cluster node registered from this instance
    TERMINATING   surplus/failed: terminate issued
    TERMINATED    gone (terminal)
    FAILED        provider reported the request dead (terminal; the
                  reconciler replaces it with a fresh REQUESTED)
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

REQUESTED = "REQUESTED"
PROVISIONING = "PROVISIONING"
RUNNING = "RUNNING"
JOINED = "JOINED"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
FAILED = "FAILED"

_TERMINAL = (TERMINATED, FAILED)
_ALIVE = (REQUESTED, PROVISIONING, RUNNING, JOINED)


def _default_drain_hook(ray_node_id: str, deadline_s: float,
                        reason: str) -> None:
    """Route a provider preemption notice into the co-located runtime's
    drain verb.  No-op when the manager runs without a runtime (unit
    tests, external reconcilers feeding a custom hook)."""
    from .._private.runtime import driver_runtime
    rt = driver_runtime()
    if rt is not None:
        rt.ctl_drain_node(ray_node_id, deadline_s, reason)


def _export_node_event(event: dict) -> None:
    """EXPORT_NODE record via the co-located runtime's event sink
    (best-effort: the manager also runs runtime-less in unit tests)."""
    from .._private.runtime import driver_runtime
    rt = driver_runtime()
    if rt is not None:
        try:
            rt.ctl_export_event("EXPORT_NODE", event)
        except Exception as e:
            from ..util import telemetry
            telemetry.note_swallowed("instance_manager.export", e)


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = REQUESTED
    # Idempotency key: one request id per launch decision; re-issuing the
    # same id after a crash must not create a second instance.
    request_id: str = ""
    cloud_id: str = ""          # provider's id once acknowledged
    ray_node_id: str = ""       # head's node id once joined
    os_pid: int = 0             # join matching (fake/subprocess providers)
    version: int = 0            # bumps on every persisted transition
    # Monotonic: feeds the request-timeout interval math in
    # _sync_cloud_state (an NTP step must not expire a launch early).
    # Never persisted; a restarted process re-stamps on load.
    updated_at: float = field(default_factory=time.monotonic)
    history: List[Tuple[str, float]] = field(default_factory=list)  # wall


@dataclass
class CloudInstance:
    """Provider-side view of one host."""
    cloud_id: str
    request_id: str
    node_type: str
    status: str                 # "queued" | "provisioning" | "running" |
    #                             "failed" | "terminated"
    os_pid: int = 0


@dataclass
class PreemptionNotice:
    """Advance warning that the cloud will reclaim a host (GCE spot
    preemption warning / GKE graceful-termination notice): the instance
    is still RUNNING, but dies within ``deadline_s``.  The manager turns
    this into a cluster drain via its ``drain_hook`` so work evacuates
    instead of crashing."""
    cloud_id: str
    deadline_s: float = 30.0
    reason: str = "preemption"


class CloudProvider:
    """Async cloud provider ABC (reference: v2 node_provider.py
    ICloudInstanceProvider — request/terminate return immediately, state
    arrives by polling).  Sized for GKE TPU QueuedResources: `request`
    asks for `count` hosts of `node_type` AS ONE UNIT (a slice); the
    provider reports each host as a CloudInstance carrying the request
    id, so the manager can bind hosts back to its instances.

    Idempotency contract: `request` with an already-seen request_id is a
    no-op.  `terminate` of an unknown/gone id is a no-op.  Both may be
    retried forever."""

    def request(self, request_id: str, node_type: str,
                count: int) -> None:
        raise NotImplementedError

    def describe(self) -> List[CloudInstance]:
        raise NotImplementedError

    def terminate(self, cloud_ids: List[str]) -> None:
        raise NotImplementedError

    def preemption_notices(self) -> List[PreemptionNotice]:
        """Pending reclaim warnings (metadata-server watcher on GCE, the
        eviction API elsewhere).  Default: the provider has no advance
        signal — preemptions surface only as vanished instances."""
        return []


class InstanceStore:
    """Versioned instance table with an append-only JSONL journal
    (reference: v2 instance_storage.py over the GCS KV).  Every
    transition lands on disk before the reconciler acts on it, so a
    restarted manager resumes mid-flight launches instead of repeating
    them."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._lock = threading.Lock()
        self._instances: Dict[str, Instance] = {}
        if path and os.path.exists(path):
            self._replay(path)

    def _replay(self, path: str) -> None:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                # _replay runs only from __init__, before the store is
                # published to any other thread — no lock needed.
                inst = self._instances.get(rec["instance_id"])  # ray-tpu: noqa[RT401]
                if inst is None:
                    inst = Instance(rec["instance_id"], rec["node_type"])
                    self._instances[inst.instance_id] = inst
                inst.status = rec["status"]
                inst.request_id = rec.get("request_id", inst.request_id)
                inst.cloud_id = rec.get("cloud_id", inst.cloud_id)
                inst.ray_node_id = rec.get("ray_node_id",
                                           inst.ray_node_id)
                inst.os_pid = rec.get("os_pid", inst.os_pid)
                inst.version = rec.get("version", inst.version)

    def upsert(self, inst: Instance, status: Optional[str] = None) -> None:
        with self._lock:
            if status is not None and status != inst.status:
                inst.history.append((inst.status, time.time()))
                inst.status = status
            inst.version += 1
            inst.updated_at = time.monotonic()
            self._instances[inst.instance_id] = inst
            if self._path:
                rec = {"instance_id": inst.instance_id,
                       "node_type": inst.node_type,
                       "status": inst.status,
                       "request_id": inst.request_id,
                       "cloud_id": inst.cloud_id,
                       "ray_node_id": inst.ray_node_id,
                       "os_pid": inst.os_pid,
                       "version": inst.version}
                with open(self._path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()

    def all(self) -> List[Instance]:
        with self._lock:
            return list(self._instances.values())

    def alive(self) -> List[Instance]:
        return [i for i in self.all() if i.status in _ALIVE]


class InstanceManager:
    """The reconciler: one `reconcile()` pass computes provider actions
    from (desired counts, provider view, cluster view) and persists every
    resulting transition.  Deliberately synchronous and idempotent — the
    caller loops it; crashing between any two statements and re-running
    converges to the same state (reference: v2 reconciler.py's
    sync-then-step design)."""

    def __init__(self, provider: CloudProvider,
                 store: Optional[InstanceStore] = None,
                 joined_pids: Optional[Callable[[], Dict[int, str]]] = None,
                 request_timeout_s: float = 300.0,
                 drain_hook: Optional[
                     Callable[[str, float, str], None]] = None,
                 prebuy: bool = True,
                 max_pending_prebuys: int = 2):
        self.provider = provider
        self.store = store or InstanceStore()
        # () -> {os_pid: ray_node_id} of nodes registered with the head.
        self._joined_pids = joined_pids or (lambda: {})
        self.request_timeout_s = request_timeout_s
        # Pre-buy-on-notice: an instance under a live preemption notice
        # is counted as already dead by the reconcile diff, so its
        # replacement is REQUESTED at notice time (before the deadline),
        # not after the cloud completes the reclaim.  Bounded: at most
        # ``max_pending_prebuys`` notices are discounted at once, so a
        # notice storm buys replacements in waves instead of all at
        # once.
        self.prebuy = prebuy
        self.max_pending_prebuys = max_pending_prebuys
        # cloud_ids with a live notice for a RUNNING/JOINED instance
        # (refreshed every _poll_preemption_notices pass), and victims
        # whose pre-buy was already counted (telemetry fires once).
        self._active_notices: set = set()
        self._prebuy_counted: set = set()
        # cloud_ids whose terminate call succeeded at least once — FAILED
        # entries are terminal and never pruned, so without this every
        # pass would re-send the full history of dead ids.
        self._terminate_issued: set = set()
        # (ray_node_id, deadline_s, reason) -> start a cluster drain.
        # Default: ctl_drain_node on the co-located runtime, so a
        # provider preemption notice flows straight into the drain
        # protocol without extra wiring.
        self._drain_hook = drain_hook or _default_drain_hook
        # cloud_ids whose notice already fired the drain hook (notices
        # repeat until the instance dies; the drain must fire once), and
        # those whose PREEMPTION_NOTICE event was already exported (a
        # notice can precede JOIN — event once, hook retried until the
        # node joins).
        self._drain_notified: set = set()
        self._notice_exported: set = set()

    # -- desired state ---------------------------------------------------- #

    def reconcile(self, desired: Dict[str, int]) -> None:
        """One convergence step: sync provider + cluster state into the
        table, then launch/terminate toward ``desired`` (node_type ->
        target instance count)."""
        self._poll_preemption_notices()
        live_ids = self._sync_cloud_state()
        self._sync_join_state()
        self._replace_failed(live_ids)
        # REQUESTED entries whose provider call was dropped (crash or
        # API error between persist and acknowledge) re-issue here —
        # idempotent by request id, so an acknowledged request is a
        # no-op.  Without this, the count diff below sees have == want
        # and the cluster under-provisions until request_timeout_s.
        self.retry_pending_requests()
        counts: Dict[str, int] = {}
        discounted = self._prebuy_discounts()
        for inst in self.store.alive():
            if inst.instance_id in discounted:
                continue  # doomed by a live notice: replacement buys NOW
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
        for ntype, want in desired.items():
            have = counts.get(ntype, 0)
            if want > have:
                self._launch(ntype, want - have)
            elif want < have:
                self._terminate_surplus(ntype, have - want)
        # Types with live instances but no desired entry drain to zero.
        for ntype, have in counts.items():
            if ntype not in desired and have > 0:
                self._terminate_surplus(ntype, have)

    # -- sync ------------------------------------------------------------- #

    def _poll_preemption_notices(self) -> None:
        """Turn provider reclaim warnings into cluster drains: a notice
        for a JOINED instance starts the graceful half of elasticity
        (drain -> urgent checkpoint -> planned downsize) instead of the
        crash path the eventual kill would otherwise take."""
        try:
            notices = self.provider.preemption_notices()
        except Exception:
            return  # the signal plane is best-effort; retried next pass
        if not notices:
            self._active_notices = set()
            return
        by_cloud = {i.cloud_id: i for i in self.store.all() if i.cloud_id}
        # Live notice set for the pre-buy discount: only notices naming
        # an instance the cloud could still reclaim.
        self._active_notices = {
            n.cloud_id for n in notices
            if (by_cloud.get(n.cloud_id) is not None
                and by_cloud[n.cloud_id].status in (RUNNING, JOINED))}
        # A terminated instance's dedup entries must not shadow a future
        # reissued notice for a recycled/cancelled-and-reposted id.
        for cid in list(self._drain_notified | self._prebuy_counted):
            inst = by_cloud.get(cid)
            if inst is None or inst.status in _TERMINAL:
                self._drain_notified.discard(cid)
                self._notice_exported.discard(cid)
                self._prebuy_counted.discard(cid)
        for notice in notices:
            inst = by_cloud.get(notice.cloud_id)
            if inst is None or inst.status not in (RUNNING, JOINED):
                continue
            if notice.cloud_id not in self._notice_exported:
                self._notice_exported.add(notice.cloud_id)
                _export_node_event({
                    "cloud_id": notice.cloud_id,
                    "node_id": inst.ray_node_id or None,
                    "state": "PREEMPTION_NOTICE",
                    "reason": notice.reason,
                    "deadline_s": notice.deadline_s})
            # The drain fires once the node has JOINED — a notice during
            # the boot->join window must KEEP retrying until then, not
            # be marked handled while no drain ever happened (the cloud
            # will still kill the host; the join may land first).
            if notice.cloud_id in self._drain_notified:
                continue
            if inst.status == JOINED and inst.ray_node_id:
                self._drain_notified.add(notice.cloud_id)
                try:
                    self._drain_hook(inst.ray_node_id, notice.deadline_s,
                                     notice.reason)
                except Exception as e:
                    from ..util import telemetry
                    telemetry.note_swallowed(
                        "instance_manager.drain_hook", e)

    def _prebuy_discounts(self) -> set:
        """Instance ids the reconcile diff counts as already dead: a
        live preemption notice dooms them, so discounting them makes
        ``want > have`` and the replacement is REQUESTED at notice time
        — the deadline window is spent provisioning instead of wasted.
        Bounded to ``max_pending_prebuys`` at once (a storm buys in
        waves as earlier replacements join and victims die), and
        naturally convergent: the discounted victim plus its REQUESTED
        replacement cancel out on the next pass."""
        if not self.prebuy or not self._active_notices:
            return set()
        doomed = sorted(
            (i for i in self.store.alive()
             if i.cloud_id in self._active_notices
             and i.status in (RUNNING, JOINED)),
            key=lambda i: i.cloud_id)
        out = set()
        for inst in doomed[:max(0, self.max_pending_prebuys)]:
            out.add(inst.instance_id)
            if inst.cloud_id not in self._prebuy_counted:
                self._prebuy_counted.add(inst.cloud_id)
                from ..util import telemetry
                telemetry.inc("ray_tpu_autoscaler_prebuy_total")
        return out

    def _sync_cloud_state(self) -> set:
        """Sync table statuses from one provider.describe() snapshot;
        returns the live cloud ids so _replace_failed reuses the same
        snapshot (cloud list calls are rate-limited/billed)."""
        by_request: Dict[str, List[CloudInstance]] = {}
        by_cloud_id: Dict[str, CloudInstance] = {}
        for ci in self.provider.describe():
            by_request.setdefault(ci.request_id, []).append(ci)
            by_cloud_id[ci.cloud_id] = ci
        live_ids = {cid for cid, ci in by_cloud_id.items()
                    if ci.status not in ("terminated", "failed")}
        now = time.monotonic()
        for inst in self.store.all():
            if inst.status in _TERMINAL:
                continue
            ci = by_cloud_id.get(inst.cloud_id) if inst.cloud_id else None
            if ci is None and inst.request_id:
                # Bind one unbound cloud host of our request to this
                # instance (slice hosts come up together; each binds to
                # one table entry).
                bound = {i.cloud_id for i in self.store.all()
                         if i.cloud_id}
                for cand in by_request.get(inst.request_id, ()):
                    if cand.cloud_id not in bound:
                        ci = cand
                        inst.cloud_id = ci.cloud_id
                        inst.os_pid = ci.os_pid
                        break
            if ci is None:
                if inst.status in (RUNNING, JOINED):
                    # Cloud lost it: a RUNNING/JOINED host vanishing
                    # without our terminate is a preemption — count it
                    # and say so, never silently reconcile (the goodput
                    # hit needs an attributable cause in the event log).
                    preempted = inst.cloud_id not in self._terminate_issued
                    self.store.upsert(inst, TERMINATED)
                    if preempted:
                        from ..util import telemetry
                        telemetry.inc("ray_tpu_node_preempted_total")
                        _export_node_event({
                            "cloud_id": inst.cloud_id or None,
                            "node_id": inst.ray_node_id or None,
                            "node_type": inst.node_type,
                            "state": "PREEMPTED",
                            "had_notice": inst.cloud_id in
                            self._drain_notified})
                elif inst.status == TERMINATING and inst.cloud_id:
                    # Our own terminate finished: expected, not preempted.
                    self.store.upsert(inst, TERMINATED)
                elif inst.status in (REQUESTED, PROVISIONING) and \
                        now - inst.updated_at > self.request_timeout_s:
                    self.store.upsert(inst, FAILED)
                elif inst.status == TERMINATING and \
                        now - inst.updated_at > self.request_timeout_s:
                    # Drained before its queued host ever appeared, and
                    # none materialized within the window: close it out.
                    self.store.upsert(inst, TERMINATED)
                continue
            if ci.os_pid and ci.os_pid != inst.os_pid:
                # Late pid report (host agent came up after RUNNING).
                inst.os_pid = ci.os_pid
            if ci.status == "failed":
                self.store.upsert(inst, FAILED)
            elif ci.status == "terminated":
                self.store.upsert(inst, TERMINATED)
            elif ci.status == "running":
                if inst.status in (REQUESTED, PROVISIONING):
                    inst.os_pid = ci.os_pid or inst.os_pid
                    self.store.upsert(inst, RUNNING)
            elif ci.status in ("queued", "provisioning"):
                if inst.status == REQUESTED:
                    self.store.upsert(inst, PROVISIONING)
        return live_ids

    def _sync_join_state(self) -> None:
        joined = self._joined_pids()
        if not joined:
            return
        for inst in self.store.all():
            if inst.status == RUNNING and inst.os_pid in joined:
                inst.ray_node_id = joined[inst.os_pid]
                self.store.upsert(inst, JOINED)

    def _replace_failed(self, live: set) -> None:
        """FAILED is terminal for the *instance*; the reconcile loop's
        count diff buys the replacement.  Failed-but-acked cloud
        resources are told to die once (idempotent; re-issued only until
        the call succeeds — not re-sent forever for every historical
        failure).  TERMINATING instances whose hosts the cloud still
        reports (``live``: this pass's describe snapshot) re-issue
        terminate too: a swallowed API error must not leave surplus
        hosts running indefinitely."""
        dead = []
        for i in self.store.all():
            if not i.cloud_id:
                continue
            if i.status == FAILED and i.cloud_id not in \
                    self._terminate_issued:
                dead.append(i.cloud_id)
            elif i.status == TERMINATING and i.cloud_id in live:
                dead.append(i.cloud_id)
        if dead:
            try:
                self.provider.terminate(dead)
                self._terminate_issued.update(dead)
            except Exception:
                pass  # retried next pass

    # -- actions ----------------------------------------------------------- #

    def _launch(self, node_type: str, count: int) -> None:
        """One request for the whole shortfall: a multi-host slice is
        requested as a unit (QueuedResources semantics), with one table
        entry per expected host, all sharing the request id."""
        request_id = uuid.uuid4().hex[:12]
        for _ in range(count):
            inst = Instance(instance_id=uuid.uuid4().hex[:12],
                            node_type=node_type, request_id=request_id)
            self.store.upsert(inst)
        try:
            self.provider.request(request_id, node_type, count)
        except Exception:
            # Table entries stay REQUESTED; the idempotent request is
            # re-issued by request_id on the next pass.
            pass

    def retry_pending_requests(self) -> None:
        """Re-issue provider requests for REQUESTED instances (e.g. after
        a manager restart): grouped by request id, idempotent."""
        groups: Dict[str, List[Instance]] = {}
        for inst in self.store.all():
            if inst.status == REQUESTED and inst.request_id:
                groups.setdefault(inst.request_id, []).append(inst)
        for rid, insts in groups.items():
            try:
                self.provider.request(rid, insts[0].node_type, len(insts))
            except Exception:
                pass

    def _terminate_surplus(self, node_type: str, count: int) -> None:
        # Noticed (doomed-anyway) instances first, then youngest-first,
        # never a JOINED node before an unjoined one (joined nodes hold
        # work).
        order = {REQUESTED: 0, PROVISIONING: 1, RUNNING: 2, JOINED: 3}
        cands = sorted(
            (i for i in self.store.alive() if i.node_type == node_type),
            key=lambda i: (i.cloud_id not in self._active_notices,
                           order.get(i.status, 9), -i.updated_at))
        doomed = cands[:count]
        cloud_ids = [i.cloud_id for i in doomed if i.cloud_id]
        for inst in doomed:
            # Even without a cloud_id the instance stays TERMINATING, not
            # TERMINATED: its slice request may still be live and its
            # host can materialize later — _sync_cloud_state then binds
            # it here and _replace_failed terminates it, instead of the
            # host orphaning against a terminal table entry.
            self.store.upsert(inst, TERMINATING)
        if cloud_ids:
            try:
                self.provider.terminate(cloud_ids)
                self._terminate_issued.update(cloud_ids)
            except Exception:
                pass


class FakeCloudProvider(CloudProvider):
    """In-memory provider for tests (reference:
    autoscaler/_private/fake_multi_node/node_provider.py:237): instances
    move queued -> provisioning -> running after configurable delays;
    failure injection kills a whole request (the QueuedResources
    all-or-nothing failure mode) or individual hosts."""

    def __init__(self, provision_delay_s: float = 0.0,
                 run_delay_s: float = 0.0):
        self._lock = threading.Lock()
        self._instances: Dict[str, CloudInstance] = {}
        self._created_at: Dict[str, float] = {}
        self._seen_requests: set = set()
        self.provision_delay_s = provision_delay_s
        self.run_delay_s = run_delay_s
        self.request_log: List[Tuple[str, str, int]] = []
        self._notices: Dict[str, PreemptionNotice] = {}

    def request(self, request_id: str, node_type: str, count: int) -> None:
        with self._lock:
            if request_id in self._seen_requests:
                return  # idempotent
            self._seen_requests.add(request_id)
            self.request_log.append((request_id, node_type, count))
            for i in range(count):
                cid = f"{request_id}-{i}"
                self._instances[cid] = CloudInstance(
                    cid, request_id, node_type, "queued", os_pid=0)
                self._created_at[cid] = time.monotonic()

    def describe(self) -> List[CloudInstance]:
        now = time.monotonic()
        with self._lock:
            out = []
            for cid, ci in self._instances.items():
                age = now - self._created_at[cid]
                if ci.status in ("failed", "terminated"):
                    pass
                elif age >= self.provision_delay_s + self.run_delay_s:
                    ci.status = "running"
                elif age >= self.provision_delay_s:
                    ci.status = "provisioning"
                out.append(CloudInstance(ci.cloud_id, ci.request_id,
                                         ci.node_type, ci.status,
                                         ci.os_pid))
            return out

    def terminate(self, cloud_ids: List[str]) -> None:
        with self._lock:
            for cid in cloud_ids:
                ci = self._instances.get(cid)
                if ci is not None:
                    ci.status = "terminated"

    # -- failure injection -------------------------------------------------- #

    def kill_request(self, request_id: str) -> None:
        """The whole queued/provisioning slice dies (capacity reclaim)."""
        with self._lock:
            for ci in self._instances.values():
                if ci.request_id == request_id and \
                        ci.status not in ("terminated",):
                    ci.status = "failed"

    def kill_instance(self, cloud_id: str) -> None:
        with self._lock:
            ci = self._instances.get(cloud_id)
            if ci is not None:
                ci.status = "failed"

    def preempt_notice(self, cloud_id: str, deadline_s: float = 10.0,
                       reason: str = "preemption") -> None:
        """Post a reclaim warning (the spot 30s-warning analog); the
        instance keeps running until lose_instance/kill_instance."""
        with self._lock:
            self._notices[cloud_id] = PreemptionNotice(
                cloud_id, deadline_s, reason)

    def lose_instance(self, cloud_id: str) -> None:
        """The cloud takes the host away (preemption completes): it
        disappears from describe() entirely — unlike kill_instance,
        which still reports a 'failed' record."""
        with self._lock:
            self._instances.pop(cloud_id, None)
            self._created_at.pop(cloud_id, None)

    def preemption_notices(self) -> List[PreemptionNotice]:
        with self._lock:
            return [n for n in self._notices.values()
                    if self._instances.get(n.cloud_id) is not None
                    and self._instances[n.cloud_id].status
                    not in ("failed", "terminated")]

    def mark_joined_pid(self, cloud_id: str, pid: int) -> None:
        with self._lock:
            ci = self._instances.get(cloud_id)
            if ci is not None:
                ci.os_pid = pid
