"""Rollout layer: EnvRunner (vector env + module inference) and the remote
fan-out EnvRunnerGroup.

Reference: rllib/env/single_agent_env_runner.py:66 (SingleAgentEnvRunner —
vector envs, module forward, episode postprocessing via connectors) and
rllib/env/env_runner_group.py:70 (EnvRunnerGroup — remote runners,
``sample`` fan-out with ray.get, ``sync_weights`` broadcast).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import VectorEnv
from .rl_module import DiscretePolicyModule, RLModuleSpec


class EnvRunner:
    """Collects fixed-length rollout batches with the current policy."""

    def __init__(self, env_creator: Callable, *, num_envs: int = 4,
                 module_spec: Optional[RLModuleSpec] = None,
                 seed: int = 0, explore: bool = True,
                 env_to_module=None, module=None,
                 reward_connector=None):
        import jax

        self.vec = VectorEnv(env_creator, num_envs, seed=seed)
        # Reward-path connector (reference: rllib clip_rewards): applied
        # to the per-step reward vector before it enters the batch.
        self.reward_connector = reward_connector
        # Env-to-module connector pipeline (reference: rllib ConnectorV2):
        # observations pass through it before every forward; its state
        # syncs with the weights via get_state/set_state.
        from .connectors import Connector, ConnectorPipeline
        if env_to_module is not None and \
                not isinstance(env_to_module, ConnectorPipeline):
            env_to_module = ConnectorPipeline(
                [env_to_module] if isinstance(env_to_module, Connector)
                else list(env_to_module))
        self.env_to_module = env_to_module
        obs_dim = self.vec.observation_dim
        if env_to_module is not None:
            obs_dim *= env_to_module.output_dim_factor
        self.spec = module_spec or RLModuleSpec(
            obs_dim, self.vec.num_actions)
        # Custom module hook (e.g. models.CNNPolicyModule): anything with
        # the init/forward_train-dict/forward_exploration surface.
        self.module = module if module is not None \
            else DiscretePolicyModule(self.spec)
        self.explore = explore
        self._key = jax.random.key(seed)
        self.params = self.module.init(jax.random.key(seed + 1))
        self._obs = self._connect(self.vec.reset())
        # Episode-return bookkeeping for metrics.
        self._ep_returns = np.zeros(num_envs, np.float64)
        self._ep_lens = np.zeros(num_envs, np.int64)
        self._finished_returns: List[float] = []
        self._finished_lens: List[int] = []

        # Recurrent modules (models.GRUPolicyModule surface:
        # initial_state/forward_step) carry hidden state through the
        # rollout; sample() then also records window-start states and
        # PPO trains with sequence batches (reference:
        # rllib/env/single_agent_env_runner.py:66 stateful-module
        # handling via connector pipelines).
        self.recurrent = hasattr(self.module, "initial_state") \
            and hasattr(self.module, "forward_step")
        if self.recurrent:
            self._rec_state = np.asarray(
                self.module.initial_state(num_envs), np.float32)

            def explore_rec(p, obs, state, key):
                logits, value, new_state = self.module.forward_step(
                    p, obs, state)
                action = jax.random.categorical(key, logits)
                import jax.numpy as jnp
                logp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits), action[:, None],
                    axis=-1)[:, 0]
                return action, logp, value, new_state

            self._explore_rec = jax.jit(explore_rec)
            self._step_fn = jax.jit(self.module.forward_step)
        else:
            self._explore_fn = jax.jit(self.module.forward_exploration)
            self._infer_fn = jax.jit(self.module.forward_inference)
            self._value_fn = jax.jit(
                lambda p, o: self.module.forward_train(p, o)["value"])

    def _connect(self, obs: np.ndarray) -> np.ndarray:
        return obs if self.env_to_module is None else self.env_to_module(obs)

    def get_connector_state(self) -> Dict[str, Any]:
        """Connector stats only — sync_weights must not ship the params
        pytree driver-ward just to read these."""
        return {} if self.env_to_module is None \
            else self.env_to_module.get_state()

    def set_connector_state(self, state: Dict[str, Any]) -> None:
        if self.env_to_module is not None:
            self.env_to_module.set_state(state)

    # -- weights --------------------------------------------------------- #

    def get_state(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {"params": self.params}
        if self.env_to_module is not None:
            state["connectors"] = self.env_to_module.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> bool:
        self.params = state["params"]
        if self.env_to_module is not None and "connectors" in state:
            self.env_to_module.set_state(state["connectors"])
        return True

    def set_weights(self, params) -> bool:
        """Weights-only update; called with an ObjectRef argument the
        params materialize on this worker straight from the object store
        (no driver copy)."""
        self.params = params
        return True

    # -- sampling -------------------------------------------------------- #

    def sample(self, num_steps: int = 256) -> Dict[str, np.ndarray]:
        """Rollout ``num_steps`` per sub-env; returns time-major flattened
        arrays plus bootstrap values for GAE."""
        import jax

        n, d = self.vec.num_envs, self.spec.observation_dim
        obs_buf = np.empty((num_steps, n, d), np.float32)
        act_buf = np.empty((num_steps, n), np.int32)
        logp_buf = np.empty((num_steps, n), np.float32)
        val_buf = np.empty((num_steps, n), np.float32)
        rew_buf = np.empty((num_steps, n), np.float32)
        done_buf = np.empty((num_steps, n), bool)
        term_buf = np.empty((num_steps, n), bool)
        # V(final_obs) for truncated boundaries (0 elsewhere): the GAE
        # bootstrap for episodes cut by time limits, not by termination.
        boot_buf = np.zeros((num_steps, n), np.float32)
        # Recurrent: the learner replays this window from its start
        # state, resetting at in-window episode boundaries.
        state_in = np.array(self._rec_state) if self.recurrent else None

        for t in range(num_steps):
            self._key, sub = jax.random.split(self._key)
            # Every branch lands its outputs with ONE batched
            # device->host transfer (jax.device_get of the whole
            # tuple); per-array np.asarray here cost 3 device syncs
            # per env step (RT502).
            if self.recurrent:
                if self.explore:
                    actions, logp, values, new_state = jax.device_get(
                        self._explore_rec(self.params, self._obs,
                                          self._rec_state, sub))
                else:
                    # Greedy, like the non-recurrent forward_inference
                    # contract for evaluation runners.
                    logits, _v, new_state = jax.device_get(
                        self._step_fn(self.params, self._obs,
                                      self._rec_state))
                    actions = np.argmax(logits, axis=-1)
                    logp = np.zeros(n, np.float32)
                    values = np.zeros(n, np.float32)
                self._rec_state = np.asarray(new_state)
            elif self.explore:
                actions, logp, values = jax.device_get(
                    self._explore_fn(self.params, self._obs, sub))
            else:
                actions = jax.device_get(
                    self._infer_fn(self.params, self._obs))
                logp = np.zeros(n, np.float32)
                values = np.zeros(n, np.float32)
            actions = np.asarray(actions)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            raw_obs, rewards, dones, terms, final_obs = \
                self.vec.step(actions)
            if self.env_to_module is not None and dones.any():
                # Auto-reset rows carry a fresh episode's obs: history-
                # keeping connectors must not leak old frames into it.
                self.env_to_module.on_episode_boundaries(dones)
            self._obs = self._connect(raw_obs)
            rew_buf[t] = rewards if self.reward_connector is None \
                else self.reward_connector(rewards)
            done_buf[t] = dones
            term_buf[t] = terms
            truncs = dones & ~terms
            if self.recurrent and dones.any():
                # Fresh episodes start from the zero state.  (np.asarray
                # of a jax output is read-only: build a new array.)
                self._rec_state = np.where(dones[:, None], 0.0,
                                           self._rec_state
                                           ).astype(np.float32)
            if self.explore and truncs.any():
                # Note: with a stateful FrameStack connector the truncation
                # bootstrap sees the post-step stack — an approximation the
                # reference shares (final_observation is a single frame).
                fo = final_obs if self.env_to_module is None else \
                    self.env_to_module.transform(final_obs)
                if self.recurrent:
                    # Value of the truncated final obs under the
                    # pre-reset state (the state that produced it).
                    _lg, v_dev, _st = self._step_fn(
                        self.params, fo, np.asarray(new_state))
                    vals = jax.device_get(v_dev)
                else:
                    vals = jax.device_get(self._value_fn(self.params, fo))
                boot_buf[t, truncs] = vals[truncs]
            self._ep_returns += rewards
            self._ep_lens += 1
            for i in np.nonzero(dones)[0]:
                self._finished_returns.append(float(self._ep_returns[i]))
                self._finished_lens.append(int(self._ep_lens[i]))
                self._ep_returns[i] = 0.0
                self._ep_lens[i] = 0

        # Bootstrap value for the final observation of each sub-env.
        if self.explore and self.recurrent:
            _lg, last_val, _st = self._step_fn(self.params, self._obs,
                                               self._rec_state)
            last_val = np.asarray(last_val)
        elif self.explore:
            self._key, sub = jax.random.split(self._key)
            _, _, last_val = self._explore_fn(self.params, self._obs, sub)
            last_val = np.asarray(last_val)
        else:
            last_val = np.zeros(n, np.float32)
        out = {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "terminateds": term_buf, "bootstrap_values": boot_buf,
            "last_values": last_val,
        }
        if self.recurrent:
            out["state_in"] = state_in
        return out

    def metrics(self, window: int = 100) -> Dict[str, float]:
        rets = self._finished_returns[-window:]
        lens = self._finished_lens[-window:]
        return {
            "episode_return_mean": float(np.mean(rets)) if rets else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "num_episodes": len(self._finished_returns),
        }

    def ping(self) -> str:
        return "ok"


class EnvRunnerGroup:
    """Local-or-remote set of EnvRunners (reference: env_runner_group.py:70).

    ``num_env_runners=0`` keeps one local runner (the rllib convention for
    debugging); otherwise runners are actors sampled in parallel.
    """

    def __init__(self, env_creator: Callable, *, num_env_runners: int = 0,
                 num_envs_per_runner: int = 4,
                 module_spec: Optional[RLModuleSpec] = None, seed: int = 0,
                 runner_resources: Optional[Dict[str, float]] = None,
                 env_to_module_fn=None, module_fn=None):
        self.num_env_runners = num_env_runners
        # Prototype pipeline used only for merge_states on gathered
        # per-runner connector states (its own state is never consulted).
        self._connector_proto = env_to_module_fn() if env_to_module_fn \
            else None
        if num_env_runners == 0:
            self.local = EnvRunner(
                env_creator, num_envs=num_envs_per_runner,
                module_spec=module_spec, seed=seed,
                env_to_module=env_to_module_fn and env_to_module_fn(),
                module=module_fn and module_fn())
            self.remotes = []
        else:
            import ray_tpu
            self.local = None
            cls = ray_tpu.remote(EnvRunner)
            opts = {"num_cpus": 1}
            if runner_resources:
                opts["resources"] = runner_resources
            self.remotes = [
                cls.options(**opts).remote(
                    env_creator, num_envs=num_envs_per_runner,
                    module_spec=module_spec, seed=seed + 1000 * (i + 1),
                    env_to_module=env_to_module_fn and env_to_module_fn(),
                    module=module_fn and module_fn())
                for i in range(num_env_runners)
            ]

    def sample(self, num_steps: int = 256) -> List[Dict[str, np.ndarray]]:
        if self.local is not None:
            return [self.local.sample(num_steps)]
        import ray_tpu
        return ray_tpu.get([r.sample.remote(num_steps) for r in self.remotes])

    def sync_weights(self, params) -> None:
        """Broadcast learner params to all runners; with stateful
        connectors, also merge per-runner connector stats into one
        canonical state and broadcast it back (reference:
        env_runner_group.py sync_weights + rllib's distributed
        MeanStdFilter aggregation).

        ``params`` may be an ObjectRef (from
        ``LearnerGroup.get_weights_ref``): runners then materialize the
        pytree straight from the object store and the driver never holds
        it."""
        import ray_tpu
        if self.local is not None:
            if isinstance(params, ray_tpu.ObjectRef):
                params = ray_tpu.get(params)
            self.local.set_state({"params": params})
            return
        if isinstance(params, ray_tpu.ObjectRef):
            # Top-level ref arg: resolved on each runner's node from the
            # store — no driver hop for the weights payload.
            ray_tpu.get([r.set_weights.remote(params)
                         for r in self.remotes])
            if self._connector_proto is not None:
                states = ray_tpu.get([r.get_connector_state.remote()
                                      for r in self.remotes])
                merged = self._connector_proto.merge_states(states)
                ray_tpu.get([r.set_connector_state.remote(merged)
                             for r in self.remotes])
            return
        state = {"params": params}
        if self._connector_proto is not None:
            states = ray_tpu.get([r.get_connector_state.remote()
                                  for r in self.remotes])
            state["connectors"] = self._connector_proto.merge_states(states)
        ray_tpu.get([r.set_state.remote(state) for r in self.remotes])

    def connector_state(self):
        """Canonical connector state for evaluation/inference consumers."""
        if self.local is not None:
            return self.local.get_state().get("connectors")
        if self._connector_proto is None:
            return None
        import ray_tpu
        states = ray_tpu.get([r.get_connector_state.remote()
                              for r in self.remotes])
        return self._connector_proto.merge_states(states)

    def aggregate_metrics(self) -> Dict[str, float]:
        if self.local is not None:
            return self.local.metrics()
        import ray_tpu
        all_m = ray_tpu.get([r.metrics.remote() for r in self.remotes])
        rets = [m["episode_return_mean"] for m in all_m
                if not np.isnan(m["episode_return_mean"])]
        lens = [m["episode_len_mean"] for m in all_m
                if not np.isnan(m["episode_len_mean"])]
        return {
            "episode_return_mean": float(np.mean(rets)) if rets else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "num_episodes": int(sum(m["num_episodes"] for m in all_m)),
        }

    def stop(self) -> None:
        import ray_tpu
        for r in self.remotes:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
