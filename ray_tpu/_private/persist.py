"""Head state persistence: WAL + snapshot for the controller tables.

The reference's GCS survives restarts by writing its tables through a
pluggable store (reference: src/ray/gcs/gcs_server.cc:164-189 choosing
RedisStoreClient, gcs/store_client/redis_store_client.h) and rebuilding
in-memory state from a full table read on boot (gcs_init_data.h
GcsInitData::AsyncLoad).  Here the store is a local append-only WAL plus
periodic snapshot in the head's state directory — the controller is a
single writer, so a log of pickled mutation records replayed in order
reconstructs the exact table state without any cross-table ordering
machinery.

What persists: actors (including pickled creation specs), named-actor
bindings, placement groups (bundle *shapes*; node assignments are
ephemeral and re-planned on restart), jobs, and the KV store.  What does
NOT: node registrations (nodes re-register on reconnect, reference:
raylets re-registering after GCS failover) and the object directory —
object payloads live in the dead process's shm arena, so directory
entries would dangle; lost objects are rebuilt by lineage reconstruction
on the owning driver instead.

Durability model: records are flushed (not fsynced) per append — a head
process kill (the failure mode this protects against) loses nothing in
the OS page cache; machine-level crash durability would need fsync and is
configurable via ``head_wal_fsync``.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Iterator, List, Optional

_LEN = struct.Struct("<I")

SNAPSHOT = "snapshot.bin"
WAL = "wal.bin"


class StateStore:
    """Append-only record log with snapshot compaction.

    Records are arbitrary picklable tuples; ``load()`` returns snapshot
    records then WAL records, in append order.  A torn tail (partial final
    record from a mid-write kill) is truncated silently.
    """

    def __init__(self, state_dir: str, fsync: bool = False,
                 compact_every: int = 50_000):
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self._fsync = fsync
        self._lock = threading.Lock()
        self._wal_path = os.path.join(state_dir, WAL)
        self._snap_path = os.path.join(state_dir, SNAPSHOT)
        self._wal_count = 0
        self._compact_every = compact_every
        self._wal_f = None  # opened lazily after any replay/compaction

    # -- read side ----------------------------------------------------------

    @staticmethod
    def _read_records(path: str) -> List[Any]:
        out: List[Any] = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        n = len(data)
        while off + _LEN.size <= n:
            (rec_len,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + rec_len > n:
                break  # torn tail from a mid-write kill
            try:
                out.append(pickle.loads(
                    data[off + _LEN.size: off + _LEN.size + rec_len]))
            except Exception:
                break  # corrupt tail: stop at the last good record
            off += _LEN.size + rec_len
        return out

    def load(self) -> List[Any]:
        """All records in order (snapshot first, then WAL)."""
        return (self._read_records(self._snap_path)
                + self._read_records(self._wal_path))

    # -- write side ---------------------------------------------------------

    def _ensure_open(self):
        if self._wal_f is None:
            self._wal_f = open(self._wal_path, "ab")
        return self._wal_f

    def append(self, record: Any) -> None:
        blob = pickle.dumps(record, protocol=5)
        with self._lock:
            f = self._ensure_open()
            f.write(_LEN.pack(len(blob)))
            f.write(blob)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
            self._wal_count += 1
            wal_count = self._wal_count
        # Compaction trigger reads the snapshot taken under the lock
        # (RT401): a concurrent append must not tear the threshold read.
        if wal_count >= self._compact_every and \
                self.on_compact is not None:
            try:
                self.on_compact()
            except Exception:
                pass

    # Set by the owner to a zero-arg callable that calls compact() with the
    # current full state (the store can't snapshot tables it doesn't own).
    on_compact: Optional[Any] = None

    def compact(self, records: List[Any]) -> None:
        """Replace snapshot+WAL with one snapshot of ``records``."""
        with self._lock:
            tmp = self._snap_path + ".tmp"
            with open(tmp, "wb") as f:
                for r in records:
                    blob = pickle.dumps(r, protocol=5)
                    f.write(_LEN.pack(len(blob)))
                    f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._snap_path)
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None
            try:
                os.unlink(self._wal_path)
            except FileNotFoundError:
                pass
            self._wal_count = 0

    def close(self) -> None:
        with self._lock:
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None
