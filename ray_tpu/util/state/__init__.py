"""State API: programmatic views of cluster state.

Reference: python/ray/util/state/api.py (list_actors:793, list_tasks:1020,
list_nodes, list_objects, list_placement_groups, list_jobs, summarize_*)
served by dashboard/modules/state/state_head.py over GcsTaskManager.  Here
the queries hit the driver runtime's controller + TaskEventBuffer directly
(or over the worker control channel when called inside a task/actor).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private.api import _control


def list_tasks(filters: Optional[List] = None,
               limit: int = 10000, **_: Any) -> List[Dict[str, Any]]:
    """Task event records. ``filters`` is a list of (key, "=", value)
    triples like the reference's predicate filters."""
    fd = None
    if filters:
        fd = {}
        for key, op, value in filters:
            if op not in ("=", "=="):
                raise ValueError(f"only equality filters supported, got {op}")
            fd[key] = value
    return _control("list_tasks", fd, limit)


def list_actors(**_: Any) -> List[Dict[str, Any]]:
    return _control("list_actors")


def list_nodes(**_: Any) -> List[Dict[str, Any]]:
    return _control("nodes")


def list_objects(limit: int = 10000, **_: Any) -> List[Dict[str, Any]]:
    return _control("list_objects", limit)


def list_placement_groups(**_: Any) -> List[Dict[str, Any]]:
    return _control("list_placement_groups")


def list_jobs(**_: Any) -> List[Dict[str, Any]]:
    return _control("list_jobs")


def summarize_tasks(**_: Any) -> Dict[str, Dict[str, int]]:
    """name -> {state -> count} (reference: api.py summarize_tasks)."""
    return _control("summarize_tasks")


def summarize_actors(**_: Any) -> Dict[str, Dict[str, int]]:
    out: Dict[str, Dict[str, int]] = {}
    for a in list_actors():
        per = out.setdefault(a.get("class_name") or "<unknown>", {})
        per[a["state"]] = per.get(a["state"], 0) + 1
    return out


def get_task(task_id: str) -> Optional[Dict[str, Any]]:
    for t in list_tasks():
        if t["task_id"] == task_id:
            return t
    return None


def get_actor(actor_id: str) -> Optional[Dict[str, Any]]:
    for a in list_actors():
        if a["actor_id"] == actor_id:
            return a
    return None


class profile_span:
    """Context manager recording a user span onto the timeline
    (reference: ray.profiling / ProfileEvent, core_worker/profile_event.h).

    Example::

        with state.profile_span("load_batch", category="data"):
            ...
    """

    def __init__(self, name: str, category: str = "user",
                 pid: str = "user", tid: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None):
        import os
        import threading
        self.name = name
        self.category = category
        self.pid = pid
        self.tid = tid or f"pid:{os.getpid()}:{threading.get_ident() % 10000}"
        self.extra = extra

    def __enter__(self):
        import time
        self._start = time.time()
        return self

    def __exit__(self, *exc):
        import time
        _control("add_profile_span", self.name, self.category, self._start,
                 time.time(), self.pid, self.tid, self.extra)
        return False


def timeline(filename: Optional[str] = None) -> str:
    """Chrome-trace JSON of task execution (reference: `ray timeline`,
    _private/state.py:471 chrome_tracing_dump). Returns the JSON string and
    optionally writes it to ``filename``."""
    trace = _control("timeline")
    payload = json.dumps(trace)
    if filename:
        with open(filename, "w") as f:
            f.write(payload)
    return payload
