"""Dashboard HTTP server: JSON state APIs + Prometheus metrics + overview.

Endpoints (reference: dashboard/modules/*):
    GET /                       — HTML overview
    GET /api/cluster            — resources, node/actor/task counts
    GET /api/nodes              — node table (state API)
    GET /api/actors             — actor table
    GET /api/tasks?limit=N      — task events
    GET /api/tasks/summary      — per-function state counts
    GET /api/sched              — scheduler queue depths, decision rates,
                                  event-buffer health (?decisions=N adds
                                  decision-ring records)
    GET /api/tasks/explain?task_id=ID — why pending / why that node
    GET /api/objects            — object directory (owner node + store
                                  state attributed per object)
    GET /api/memory             — per-node object-store occupancy, top
                                  objects, leak candidates
    GET /api/objects/explain?object_id=ID — one object's location,
                                  producer and store lifecycle
    GET /api/placement_groups   — PG table
    GET /api/jobs               — job table
    GET /api/timeline           — chrome-trace events
    GET /api/metrics/summary    — built-in telemetry by subsystem + goodput
    GET /api/serve/fleet        — published decode-fleet snapshots
                                  (llm.fleet: replicas, router, autoscale)
    GET /api/stacks             — cluster-wide stack capture (`ray stack`)
    POST /api/debug/dump        — write a flight-recorder bundle
    POST /api/profile           — on-demand cluster profile (merged
                                  clock-aligned Chrome trace)
    GET /metrics                — Prometheus exposition (user + built-in)
    GET /-/healthz              — liveness
"""

from __future__ import annotations

from .._private import aioloop as _aioloop

import json
import threading
from typing import Optional

_PAGE = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 8px}h2{margin-top:1.2em}</style>
</head><body><h1>ray_tpu</h1>
<div id=out>loading…</div>
<script>
async function refresh(){
  const c = await (await fetch('/api/cluster')).json();
  const nodes = await (await fetch('/api/nodes')).json();
  const actors = await (await fetch('/api/actors')).json();
  const summary = await (await fetch('/api/tasks/summary')).json();
  const telem = await (await fetch('/api/metrics/summary')).json();
  const sched = await (await fetch('/api/sched')).json();
  const mem = await (await fetch('/api/memory')).json();
  const fleet = await (await fetch('/api/serve/fleet')).json();
  let h = '<h2>cluster</h2><table>';
  for (const [k,v] of Object.entries(c.total_resources))
    h += `<tr><td>${k}</td><td>${c.available_resources[k]??0} / ${v}</td></tr>`;
  h += '</table><h2>nodes</h2><table><tr><th>id</th><th>state</th><th>host</th><th>head</th></tr>';
  for (const n of nodes) h += `<tr><td>${n.node_id.slice(0,12)}</td><td>${n.alive?(n.draining?`DRAINING(${Math.round(n.drain_remaining_s)}s)`:'ALIVE'):'DEAD'}</td><td>${n.hostname}</td><td>${n.is_head}</td></tr>`;
  h += '</table><h2>actors</h2><table><tr><th>id</th><th>class</th><th>state</th><th>restarts</th></tr>';
  for (const a of actors) h += `<tr><td>${a.actor_id.slice(0,12)}</td><td>${a.class_name}</td><td>${a.state}</td><td>${a.num_restarts}</td></tr>`;
  h += '</table><h2>tasks</h2><table><tr><th>name</th><th>states</th></tr>';
  for (const [name,states] of Object.entries(summary))
    h += `<tr><td>${name}</td><td>${JSON.stringify(states)}</td></tr>`;
  h += '</table>';
  // Scheduler telescope: queue depths, decision rates, and event-ring
  // saturation (dropped/backlog must be visible, never silent).
  const ss = sched.stats;
  h += '<h2>scheduler</h2><table>'
    + `<tr><td>decisions/s (5s)</td><td>${ss.rates.decisions_per_s_5s}</td></tr>`
    + `<tr><td>decisions total</td><td>${ss.decisions.total} (ring dropped ${ss.decisions.num_dropped})</td></tr>`;
  for (const [q,d] of Object.entries(ss.queues))
    h += `<tr><td>queue ${q}</td><td>${d}</td></tr>`;
  h += `<tr><td>task events</td><td>${ss.events.num_events}/${ss.events.capacity} `
    + `(dropped ${ss.events.num_dropped}, fold backlog ${ss.events.fold_backlog})</td></tr>`;
  h += '</table>';
  // Data-plane telescope: per-node store occupancy + leak candidates.
  const mb = b => (b / 1048576).toFixed(1) + ' MB';
  h += '<h2>object store</h2><table>'
    + '<tr><th>node</th><th>used/capacity</th><th>pinned</th>'
    + '<th>spilled</th><th>objects</th></tr>';
  for (const [nid, s] of Object.entries(mem.nodes || {}))
    h += `<tr><td>${nid.slice(0,12)}</td>`
      + `<td>${mb(s.used_bytes||0)} / ${mb(s.capacity_bytes||0)}</td>`
      + `<td>${mb(s.pinned_bytes||0)}</td><td>${mb(s.spilled_bytes||0)}</td>`
      + `<td>${s.num_objects||0}</td></tr>`;
  h += '</table>';
  for (const l of mem.leak_candidates || [])
    h += `<p>leak candidate: ${l.object_id.slice(0,16)}… `
      + `${mb(l.nbytes||0)} ${l.reason}</p>`;
  // Serving fleet: per-replica decode state + autoscale posture
  // (published by llm.fleet FleetServer instances via the cluster KV).
  for (const f of fleet.fleets || []) {
    h += `<h2>serving fleet: ${f.name}</h2>`
      + `<p>replicas ${(f.replicas||[]).length}/${f.target_replicas} `
      + `queue ${f.router_queue} completed ${f.completed} `
      + `shed ${f.shed} rebalances ${f.rebalances}</p>`
      + '<table><tr><th>replica</th><th>state</th><th>ongoing</th>'
      + '<th>waiting</th><th>kv%</th><th>cache</th><th>hit rate</th></tr>';
    for (const r of f.replicas || []) {
      const cache = r.cache || {};
      h += `<tr><td>${r.name}</td><td>${r.state}</td>`
        + `<td>${r.ongoing}</td><td>${r.waiting}</td>`
        + `<td>${((r.kv_occupancy||0)*100).toFixed(0)}%</td>`
        + `<td>${cache.entries||0} / ${mb(cache.bytes||0)}</td>`
        + `<td>${(cache.hit_rate||0).toFixed(2)}</td></tr>`;
    }
    h += '</table>';
    if (f.autoscale)
      h += `<p>autoscale: queue/replica ${f.autoscale.signals.queue_per_replica?.toFixed(2)} `
        + `shed/s ${f.autoscale.signals.shed_rate?.toFixed(3)} `
        + `burning ${(f.autoscale.burning_for_s ?? 0).toFixed(1)}s `
        + `idle ${(f.autoscale.idle_for_s ?? 0).toFixed(1)}s `
        + `cooldown ${f.autoscale.cooldown_remaining_s.toFixed(1)}s</p>`;
  }
  // Built-in system telemetry: serving / training / llm / data metrics.
  h += '<h2>system telemetry</h2>';
  if (telem.goodput)
    h += `<p>train goodput: ${telem.goodput.goodput_ratio.toFixed(3)} `
      + `(productive ${telem.goodput.productive_s.toFixed(1)}s / `
      + `total ${telem.goodput.total_s.toFixed(1)}s)</p>`;
  for (const [sub, metrics] of Object.entries(telem.subsystems || {})) {
    h += `<h3>${sub}</h3><table><tr><th>metric</th><th>tags</th>`
      + '<th>value</th></tr>';
    for (const [name, m] of Object.entries(metrics))
      for (const s of m.samples) {
        const unit = name.endsWith('_seconds') ? 's' : '';
        const v = m.type === 'histogram'
          ? `n=${s.count} mean=${s.mean.toFixed(4)}${unit}` : s.value;
        h += `<tr><td title="${m.description}">${name}</td>`
          + `<td>${JSON.stringify(s.tags)}</td><td>${v}</td></tr>`;
      }
    h += '</table>';
  }
  document.getElementById('out').innerHTML = h;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class DashboardServer:
    def __init__(self, runtime, port: int = 0, host: str = "127.0.0.1"):
        self.runtime = runtime
        self._started = threading.Event()
        self._loop = None
        self._error: Optional[BaseException] = None
        self.port: Optional[int] = None
        self._thread = threading.Thread(
            target=self._serve, args=(host, port), name="dashboard",
            daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("dashboard failed to start")
        if self._error is not None:
            raise RuntimeError(
                f"dashboard failed to start: {self._error!r}")

    # -- handlers -----------------------------------------------------------

    def _json(self, payload):
        from aiohttp import web
        return web.Response(text=json.dumps(payload, default=str),
                            content_type="application/json")

    def _routes(self, app):
        from aiohttp import web
        rt = self.runtime

        async def index(req):
            return web.Response(text=_PAGE, content_type="text/html")

        async def cluster(req):
            return self._json({
                "total_resources": rt.ctl_cluster_resources(),
                "available_resources": rt.ctl_available_resources(),
                "num_nodes": len(rt.controller.nodes),
                "num_actors": len(rt.controller.actors),
            })

        async def nodes(req):
            return self._json(rt.ctl_nodes())

        async def actors(req):
            return self._json(rt.ctl_list_actors())

        async def tasks(req):
            limit = int(req.query.get("limit", 1000))
            return self._json(rt.ctl_list_tasks(limit=limit))

        async def tasks_summary(req):
            return self._json(rt.ctl_summarize_tasks())

        async def sched(req):
            # Control-plane telescope: queue depths, decision rates,
            # event-buffer saturation; ?decisions=N adds ring records.
            try:
                n = int(req.query.get("decisions", 0))
            except ValueError:
                return web.Response(status=400, text="bad decisions")
            out = {"stats": rt.ctl_sched_stats()}
            if n > 0:
                out["decisions"] = rt.ctl_sched_decisions(None, n)
            return self._json(out)

        async def task_explain(req):
            task_id = req.query.get("task_id", "")
            if not task_id:
                return web.Response(status=400, text="task_id required")
            return self._json(rt.ctl_explain_task(task_id))

        async def objects(req):
            return self._json(rt.ctl_list_objects())

        async def memory_summary(req):
            # Data-plane telescope: per-node occupancy, top objects by
            # size, leak candidates (`ray-tpu memory` shape).
            try:
                top_n = int(req.query.get("top_n", 10))
            except ValueError:
                return web.Response(status=400, text="bad top_n")
            return self._json(rt.ctl_memory_summary(top_n))

        async def object_explain(req):
            object_id = req.query.get("object_id", "")
            if not object_id:
                return web.Response(status=400, text="object_id required")
            return self._json(rt.ctl_explain_object(object_id))

        async def pgs(req):
            return self._json(rt.ctl_list_placement_groups())

        async def jobs(req):
            return self._json(rt.ctl_list_jobs())

        async def timeline(req):
            return self._json(rt.ctl_timeline())

        async def node_views(req):
            # Syncer load views (reference: resource view in the node
            # table feed).
            return self._json(rt.ctl_node_views())

        async def logs(req):
            return self._json(rt.ctl_log_files())

        async def log_tail(req):
            fname = req.match_info["fname"]
            n = int(req.query.get("lines", 100))
            return self._json(rt.ctl_log_tail(fname, n))

        async def metrics(req):
            from ..util.metrics import prometheus_text
            return web.Response(text=prometheus_text(),
                                content_type="text/plain")

        async def metrics_summary(req):
            from ..util import telemetry
            return self._json(telemetry.summary())

        async def metrics_history(req):
            # Sparkline JSON from the head's time-series store
            # (ray_tpu.metricsview): per matching series a list of
            # [age_s, value] rows, newest age ~0.  ?name= is required;
            # ?window=, ?points= and repeated ?tag=k=v refine it.
            name = req.query.get("name", "")
            if not name:
                return web.Response(status=400, text="name required")
            try:
                window_s = float(req.query.get("window", 300))
                max_points = int(req.query.get("points", 240))
                from ..metricsview import parse_tag_args
                tags = parse_tag_args(req.query.getall("tag", []))
            except ValueError as e:
                return web.Response(status=400, text=str(e))
            return self._json(rt.ctl_metrics_history(
                name, window_s, tags, max_points))

        async def metrics_query(req):
            name = req.query.get("name", "")
            if not name:
                return web.Response(status=400, text="name required")
            try:
                window_s = float(req.query.get("window", 60))
                agg = req.query.get("agg", "avg")
                from ..metricsview import parse_tag_args, validate_agg
                tags = parse_tag_args(req.query.getall("tag", []))
                if not validate_agg(agg):
                    raise ValueError(f"unknown agg {agg!r}")
            except ValueError as e:
                return web.Response(status=400, text=str(e))
            return self._json(rt.ctl_metrics_query(
                name, window_s, agg, tags))

        async def alerts(req):
            return self._json(rt.ctl_alerts(
                int(req.query.get("recent", 50))))

        async def stacks(req):
            # Cluster-wide stack capture (reference: `ray stack`).  The
            # collection blocks up to its timeout — exactly when a worker
            # is hung — so it runs in an executor: /-/healthz and the
            # other routes must stay live during a hang investigation.
            import asyncio
            timeout = req.query.get("timeout_s")
            try:
                t = float(timeout) if timeout else None
            except ValueError:
                return web.Response(status=400, text="bad timeout_s")
            dump = await asyncio.get_running_loop().run_in_executor(
                None, lambda: rt.ctl_stack_dump(t))
            return self._json(dump)

        async def debug_dump(req):
            # Flight recorder on demand: writes <session>/debug/<ts>/.
            # Off-loop for the same reason as /api/stacks (it embeds a
            # stack capture).
            import asyncio
            reason = req.query.get("reason", "manual")
            path = await asyncio.get_running_loop().run_in_executor(
                None, lambda: rt.ctl_debug_dump(reason))
            return self._json({"path": path})

        async def profile(req):
            # On-demand cluster profile: blocks for the whole capture
            # window, so off-loop like /api/stacks.  ?include_trace=0
            # returns only the summary (the merged trace is on disk).
            import asyncio
            try:
                duration = float(req.query.get("duration_s", "2"))
                hz = float(req.query.get("hz", "67"))
            except ValueError:
                return web.Response(status=400,
                                    text="bad duration_s/hz")
            jax_profile = req.query.get("jax") == "1"
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: rt.ctl_profile(duration, hz, jax_profile))
            if req.query.get("include_trace") == "0":
                out = {k: v for k, v in out.items() if k != "trace"}
            return self._json(out)

        async def serve_fleet(req):
            # Published decode-fleet snapshots: each llm.fleet
            # FleetServer writes its status() JSON to the cluster KV
            # under serve:fleet:<name> (same feed as `ray-tpu serve
            # status`).
            fleets = []
            for key in sorted(rt.ctl_kv_keys("serve:fleet:")):
                raw = rt.ctl_kv_get(key)
                if raw is None:
                    continue
                try:
                    fleets.append(json.loads(raw.decode()))
                except Exception:
                    continue
            return self._json({"fleets": fleets})

        async def healthz(req):
            return web.Response(text="ok")

        app.router.add_get("/", index)
        app.router.add_get("/api/cluster", cluster)
        app.router.add_get("/api/nodes", nodes)
        app.router.add_get("/api/actors", actors)
        app.router.add_get("/api/tasks", tasks)
        app.router.add_get("/api/tasks/summary", tasks_summary)
        app.router.add_get("/api/sched", sched)
        app.router.add_get("/api/tasks/explain", task_explain)
        app.router.add_get("/api/objects", objects)
        app.router.add_get("/api/memory", memory_summary)
        app.router.add_get("/api/objects/explain", object_explain)
        app.router.add_get("/api/placement_groups", pgs)
        app.router.add_get("/api/jobs", jobs)
        app.router.add_get("/api/timeline", timeline)
        app.router.add_get("/api/metrics/summary", metrics_summary)
        app.router.add_get("/api/metrics/history", metrics_history)
        app.router.add_get("/api/metrics/query", metrics_query)
        app.router.add_get("/api/alerts", alerts)
        app.router.add_get("/api/serve/fleet", serve_fleet)
        app.router.add_get("/api/stacks", stacks)
        app.router.add_post("/api/debug/dump", debug_dump)
        app.router.add_post("/api/profile", profile)
        app.router.add_get("/api/node_views", node_views)
        app.router.add_get("/api/logs", logs)
        app.router.add_get("/api/logs/{fname}", log_tail)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/-/healthz", healthz)

    # -- lifecycle ----------------------------------------------------------

    def _serve(self, host: str, port: int):
        import asyncio

        from aiohttp import web

        async def main():
            app = web.Application()
            self._routes(app)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, host, port)
            await site.start()
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            while True:
                await asyncio.sleep(3600)

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(main())
        except Exception as e:  # noqa: BLE001
            if not self._started.is_set():
                self._error = e
                self._started.set()
        finally:
            # Executor + loop retirement shared across the three
            # daemon-loop servers (see _private/aioloop.py).
            _aioloop.shutdown_loop(self._loop)

    def stop(self):
        _aioloop.stop_loop_thread(self._loop, self._thread)


def start_dashboard(port: int = 0, host: str = "127.0.0.1"
                    ) -> DashboardServer:
    """Start the dashboard against the current driver runtime."""
    from .._private.runtime import driver_runtime
    rt = driver_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() first")
    return DashboardServer(rt, port=port, host=host)
