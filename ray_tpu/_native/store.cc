// Native shared-memory object store (plasma equivalent).
//
// Reference analog: src/ray/object_manager/plasma/ — PlasmaStore (store.h:55),
// dlmalloc shm arena (dlmalloc.cc), LRU eviction (eviction_policy.cc), and the
// raylet's spill/restore path (src/ray/raylet/local_object_manager.h:46).
//
// Design (TPU-native): one POSIX shm arena per node process, managed by a
// best-fit free-list allocator with offset coalescing.  Objects are immutable
// once sealed; any process on the host maps the arena by name and reads a
// sealed object zero-copy at its offset.  Readers are protected by plasma
// style client pinning: the owner pins an object while a descriptor to it is
// outstanding, and pinned objects are never evicted, so offsets handed out
// stay valid.  Under memory pressure, sealed unpinned objects spill to disk
// in LRU order and restore on demand (possibly at a new offset — which is why
// descriptors are always refreshed through lookup_pin at hand-out time).
//
// The store index and allocator metadata live in the owner process only; the
// arena itself is the shared medium.  Exposed as a C ABI for ctypes.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;  // cache-line alignment for payload starts

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

using Key = std::string;  // raw object-id bytes

std::string hex(const Key &k) {
  static const char *digits = "0123456789abcdef";
  std::string out;
  out.reserve(k.size() * 2);
  for (unsigned char c : k) {
    out.push_back(digits[c >> 4]);
    out.push_back(digits[c & 0xf]);
  }
  return out;
}

struct Entry {
  uint64_t offset = 0;
  uint64_t nbytes = 0;
  bool sealed = false;
  bool in_memory = true;  // false => spilled to disk
  bool deleted = false;   // delete requested while pinned; freed on last unpin
  int64_t pinned = 0;
  std::list<Key>::iterator lru_it;
  bool in_lru = false;
};

class Allocator {
  // Best-fit free-list with coalescing. free_by_size_ is the search index,
  // free_by_off_ the coalescing index; they mirror each other.
 public:
  explicit Allocator(uint64_t capacity) : capacity_(capacity) {
    insert_free(0, capacity);
  }

  int64_t allocate(uint64_t nbytes) {
    nbytes = align_up(std::max<uint64_t>(nbytes, 1));
    auto it = free_by_size_.lower_bound(nbytes);
    if (it == free_by_size_.end()) return -1;
    uint64_t size = it->first, off = it->second;
    erase_free(off, size);
    if (size > nbytes) insert_free(off + nbytes, size - nbytes);
    used_ += nbytes;
    return static_cast<int64_t>(off);
  }

  void deallocate(uint64_t off, uint64_t nbytes) {
    nbytes = align_up(std::max<uint64_t>(nbytes, 1));
    used_ -= nbytes;
    // coalesce with next
    auto next = free_by_off_.find(off + nbytes);
    if (next != free_by_off_.end()) {
      uint64_t nsize = next->second;
      erase_free(off + nbytes, nsize);
      nbytes += nsize;
    }
    // coalesce with prev
    auto prev = free_by_off_.lower_bound(off);
    if (prev != free_by_off_.begin()) {
      --prev;
      if (prev->first + prev->second == off) {
        uint64_t poff = prev->first, psize = prev->second;
        erase_free(poff, psize);
        off = poff;
        nbytes += psize;
      }
    }
    insert_free(off, nbytes);
  }

  uint64_t used() const { return used_; }
  uint64_t capacity() const { return capacity_; }

 private:
  void insert_free(uint64_t off, uint64_t size) {
    free_by_off_[off] = size;
    free_by_size_.emplace(size, off);
  }
  void erase_free(uint64_t off, uint64_t size) {
    free_by_off_.erase(off);
    auto range = free_by_size_.equal_range(size);
    for (auto it = range.first; it != range.second; ++it) {
      if (it->second == off) {
        free_by_size_.erase(it);
        break;
      }
    }
  }

  uint64_t capacity_;
  uint64_t used_ = 0;
  std::map<uint64_t, uint64_t> free_by_off_;
  std::multimap<uint64_t, uint64_t> free_by_size_;
};

}  // namespace

struct RtsStore {
  std::string seg_name;   // without leading '/'
  std::string spill_dir;
  int fd = -1;
  uint8_t *base = nullptr;
  Allocator alloc;
  std::unordered_map<Key, Entry> table;
  std::list<Key> lru;  // front = coldest
  std::mutex mu;
  uint64_t num_spilled = 0, num_restored = 0, num_evictions = 0;
  std::string last_error;

  explicit RtsStore(uint64_t cap) : alloc(cap) {}

  std::string spill_path(const Key &k) const { return spill_dir + "/" + hex(k); }

  void lru_touch(Entry &e, const Key &k) {
    if (e.in_lru) lru.erase(e.lru_it);
    lru.push_back(k);
    e.lru_it = std::prev(lru.end());
    e.in_lru = true;
  }

  void lru_remove(Entry &e) {
    if (e.in_lru) {
      lru.erase(e.lru_it);
      e.in_lru = false;
    }
  }

  bool spill_one() {
    // Spill the coldest sealed, unpinned, in-memory object. Returns false if
    // nothing is evictable.
    for (auto it = lru.begin(); it != lru.end(); ++it) {
      auto t = table.find(*it);
      if (t == table.end()) continue;
      Entry &e = t->second;
      if (!e.sealed || e.pinned > 0 || !e.in_memory) continue;
      if (spill_dir.empty()) return false;
      std::string path = spill_path(*it);
      FILE *f = std::fopen(path.c_str(), "wb");
      if (!f) return false;
      size_t n = std::fwrite(base + e.offset, 1, e.nbytes, f);
      std::fclose(f);
      if (n != e.nbytes) {
        std::remove(path.c_str());
        return false;
      }
      alloc.deallocate(e.offset, e.nbytes);
      e.in_memory = false;
      Key key = *it;
      lru_remove(e);
      ++num_spilled;
      ++num_evictions;
      (void)key;
      return true;
    }
    return false;
  }

  int64_t allocate_locked(uint64_t nbytes) {
    int64_t off = alloc.allocate(nbytes);
    while (off < 0) {
      if (!spill_one()) return -1;
      off = alloc.allocate(nbytes);
    }
    return off;
  }

  // Returns 0 ok; -3 on restore failure.
  int ensure_in_memory(Entry &e, const Key &k) {
    if (e.in_memory) return 0;
    int64_t off = allocate_locked(e.nbytes);
    if (off < 0) return -3;
    std::string path = spill_path(k);
    FILE *f = std::fopen(path.c_str(), "rb");
    if (!f) {
      alloc.deallocate(off, e.nbytes);
      return -3;
    }
    size_t n = std::fread(base + off, 1, e.nbytes, f);
    std::fclose(f);
    if (n != e.nbytes) {
      alloc.deallocate(off, e.nbytes);
      return -3;
    }
    std::remove(path.c_str());
    e.offset = static_cast<uint64_t>(off);
    e.in_memory = true;
    ++num_restored;
    return 0;
  }
};

extern "C" {

// Create the arena. `name` is the shm segment name without leading slash
// (must be unique per store); `spill_dir` may be "" to disable spilling.
RtsStore *rts_create(const char *name, uint64_t capacity, const char *spill_dir) {
  auto *s = new RtsStore(capacity);
  s->seg_name = name;
  s->spill_dir = spill_dir ? spill_dir : "";
  std::string path = "/" + s->seg_name;
  shm_unlink(path.c_str());  // stale segment from a crashed predecessor
  s->fd = shm_open(path.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (s->fd < 0) {
    delete s;
    return nullptr;
  }
  if (ftruncate(s->fd, static_cast<off_t>(capacity)) != 0) {
    close(s->fd);
    shm_unlink(path.c_str());
    delete s;
    return nullptr;
  }
  void *p = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, s->fd, 0);
  if (p == MAP_FAILED) {
    close(s->fd);
    shm_unlink(path.c_str());
    delete s;
    return nullptr;
  }
  s->base = static_cast<uint8_t *>(p);
  if (!s->spill_dir.empty()) {
    ::mkdir(s->spill_dir.c_str(), 0700);
  }
  return s;
}

const char *rts_segment_name(RtsStore *s) { return s->seg_name.c_str(); }

// Offset >= 0 on success; -1 = out of memory (after eviction); -2 = exists.
int64_t rts_allocate(RtsStore *s, const uint8_t *id, uint32_t idlen, uint64_t nbytes) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->table.count(k)) return -2;
  int64_t off = s->allocate_locked(nbytes);
  if (off < 0) return -1;
  Entry e;
  e.offset = static_cast<uint64_t>(off);
  e.nbytes = nbytes;
  auto res = s->table.emplace(std::move(k), e);
  s->lru_touch(res.first->second, res.first->first);
  return off;
}

int rts_seal(RtsStore *s, const uint8_t *id, uint32_t idlen) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->table.find(k);
  if (it == s->table.end() || it->second.deleted) return -1;
  it->second.sealed = true;
  return 0;
}

// 0 ok (offset/nbytes filled; pinned if do_pin); -1 missing; -2 unsealed;
// -3 restore failed (spill file lost or arena too full of pinned objects).
int rts_lookup_pin(RtsStore *s, const uint8_t *id, uint32_t idlen, int do_pin,
                   uint64_t *offset, uint64_t *nbytes) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->table.find(k);
  if (it == s->table.end() || it->second.deleted) return -1;
  Entry &e = it->second;
  if (!e.sealed) return -2;
  int rc = s->ensure_in_memory(e, it->first);
  if (rc != 0) return rc;
  if (do_pin) {
    e.pinned += 1;
  }
  s->lru_touch(e, it->first);
  *offset = e.offset;
  *nbytes = e.nbytes;
  return 0;
}

int rts_unpin(RtsStore *s, const uint8_t *id, uint32_t idlen) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->table.find(k);
  if (it == s->table.end()) return -1;
  Entry &e = it->second;
  if (e.pinned > 0) e.pinned -= 1;
  if (e.pinned == 0 && e.deleted) {
    // Deferred delete: the last reader is gone, reclaim now.
    if (e.in_memory) {
      s->alloc.deallocate(e.offset, e.nbytes);
    } else {
      std::remove(s->spill_path(k).c_str());
    }
    s->lru_remove(e);
    s->table.erase(it);
  }
  return 0;
}

int rts_contains(RtsStore *s, const uint8_t *id, uint32_t idlen) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->table.find(k);
  return (it != s->table.end() && it->second.sealed &&
          !it->second.deleted) ? 1 : 0;
}

// Delete. If readers hold pins the entry is hidden immediately (lookups
// fail) but the block is reclaimed only on the last unpin, so live
// zero-copy views never see the slot reused under them.
int rts_delete(RtsStore *s, const uint8_t *id, uint32_t idlen) {
  Key k(reinterpret_cast<const char *>(id), idlen);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->table.find(k);
  if (it == s->table.end() || it->second.deleted) return -1;
  Entry &e = it->second;
  if (e.pinned > 0) {
    e.deleted = true;
    s->lru_remove(e);
    return 0;
  }
  if (e.in_memory) {
    s->alloc.deallocate(e.offset, e.nbytes);
  } else {
    std::remove(s->spill_path(k).c_str());
  }
  s->lru_remove(e);
  s->table.erase(it);
  return 0;
}

// out: [num_objects, used, capacity, spilled, restored, evictions,
//       num_in_memory, pinned_count, pinned_bytes, spilled_bytes]
// (rebuilt-by-hash with its ctypes binding, so widening is safe)
void rts_stats(RtsStore *s, uint64_t out[10]) {
  std::lock_guard<std::mutex> g(s->mu);
  uint64_t in_mem = 0, pinned = 0, pinned_bytes = 0, spilled_bytes = 0;
  for (auto &kv : s->table) {
    if (kv.second.in_memory) {
      ++in_mem;
    } else {
      spilled_bytes += kv.second.nbytes;
    }
    if (kv.second.pinned > 0) {
      ++pinned;
      pinned_bytes += kv.second.nbytes;
    }
  }
  out[0] = s->table.size();
  out[1] = s->alloc.used();
  out[2] = s->alloc.capacity();
  out[3] = s->num_spilled;
  out[4] = s->num_restored;
  out[5] = s->num_evictions;
  out[6] = in_mem;
  out[7] = pinned;
  out[8] = pinned_bytes;
  out[9] = spilled_bytes;
}

void rts_destroy(RtsStore *s) {
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto &kv : s->table) {
      if (!kv.second.in_memory) std::remove(s->spill_path(kv.first).c_str());
    }
    s->table.clear();
  }
  if (s->base) munmap(s->base, s->alloc.capacity());
  if (s->fd >= 0) close(s->fd);
  shm_unlink(("/" + s->seg_name).c_str());
  delete s;
}

}  // extern "C"
