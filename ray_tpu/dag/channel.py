"""Single-writer single-reader shared-memory channel for compiled graphs.

Reference: python/ray/experimental/channel/shared_memory_channel.py (mutable
plasma objects with writer/reader acquire-release semantics, backed by
core_worker/experimental_mutable_object_manager.cc).  Here the channel is a
raw shm segment with a seqlock-style header — the writer publishes a new
version only after the reader acknowledged the previous one, so a channel
holds at most one in-flight message and provides natural backpressure for
pipelined execution.

Layout (64-byte header, payload after):
    [ 0: 8]  write_seq  u64   — bumped by the writer after the payload lands
    [ 8:16]  payload_len u64
    [16:17]  flag        u8   — DATA / STOP / ERR
    [24:32]  read_ack    u64  — bumped by the reader after consuming
"""

from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory
from typing import Optional, Tuple

HEADER_SIZE = 64
_U64 = struct.Struct("<Q")

FLAG_DATA = 0
FLAG_STOP = 1
FLAG_ERR = 2


class ChannelTimeoutError(TimeoutError):
    pass


class ChannelClosedError(RuntimeError):
    pass


def _spin_wait(pred, timeout: Optional[float], what: str):
    deadline = None if timeout is None else time.monotonic() + timeout
    delay = 20e-6
    while not pred():
        if deadline is not None and time.monotonic() >= deadline:
            raise ChannelTimeoutError(f"timed out waiting to {what}")
        time.sleep(delay)
        delay = min(delay * 2, 1e-3)


class ShmChannel:
    """Bounded (capacity-1) message channel over a shm segment.

    Picklable: unpickling in another process attaches to the same segment.
    Exactly one process should call ``unlink`` (the creator / driver).
    """

    def __init__(self, capacity: int = 1 << 20, *, name: Optional[str] = None,
                 _create: bool = True):
        self.capacity = capacity
        if _create:
            self._shm = shared_memory.SharedMemory(
                create=True, size=HEADER_SIZE + capacity)
            self._shm.buf[:HEADER_SIZE] = b"\x00" * HEADER_SIZE
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # Only the creator (driver) owns the segment's lifetime; undo
            # the attach-side resource_tracker registration so worker exit
            # doesn't warn about / double-unlink the segment.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:
                pass
        self.name = self._shm.name
        self._closed = False

    def __reduce__(self):
        return (ShmChannel._attach, (self.name, self.capacity))

    @staticmethod
    def _attach(name: str, capacity: int) -> "ShmChannel":
        return ShmChannel(capacity, name=name, _create=False)

    # -- header accessors ---------------------------------------------------

    def _read_u64(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _write_u64(self, off: int, value: int) -> None:
        _U64.pack_into(self._shm.buf, off, value)

    # -- writer side --------------------------------------------------------

    def writable(self) -> bool:
        """True iff the reader has consumed the last message (a write now
        would not block).  Monotonic for the writer: only the writer's own
        write can flip it back to False."""
        return self._read_u64(24) == self._read_u64(0)

    def wait_writable(self, timeout: Optional[float] = None) -> None:
        _spin_wait(self.writable, timeout,
                   "write (reader has not consumed)")

    def write(self, payload: bytes, flag: int = FLAG_DATA,
              timeout: Optional[float] = None) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"serialized message ({len(payload)} B) exceeds channel "
                f"buffer ({self.capacity} B); recompile with a larger "
                "buffer_size_bytes")
        _spin_wait(self.writable, timeout,
                   "write (reader has not consumed)")
        self._shm.buf[HEADER_SIZE:HEADER_SIZE + len(payload)] = payload
        self._write_u64(8, len(payload))
        self._shm.buf[16] = flag
        # Publishing the new seq is the linearization point.
        self._write_u64(0, self._read_u64(0) + 1)

    # -- reader side --------------------------------------------------------

    def read(self, timeout: Optional[float] = None) -> Tuple[int, bytes]:
        _spin_wait(lambda: self._read_u64(0) > self._read_u64(24),
                   timeout, "read")
        flag = self._shm.buf[16]
        n = self._read_u64(8)
        payload = bytes(self._shm.buf[HEADER_SIZE:HEADER_SIZE + n])
        self._write_u64(24, self._read_u64(0))
        return flag, payload

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._shm.close()
            except Exception:
                pass

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
