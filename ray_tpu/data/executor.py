"""Streaming executor: blocks flow through fused task stages with
bounded in-flight backpressure.

Reference analog: _internal/execution/streaming_executor.py:76 (scheduling
loop :423) + operator fusion rules (_internal/logical/rules/) +
backpressure policies (_internal/execution/backpressure_policy/).
Simplifications: map-chains fuse into one remote task per block;
shuffle/repartition are barriers executed on the driver over fetched
blocks (a distributed shuffle operator is a later milestone).
"""

from __future__ import annotations

from typing import Any, Callable, List

import numpy as np

from .block import Block, BlockAccessor

# At most this many block tasks in flight (backpressure).
MAX_IN_FLIGHT = 8


def _apply_chain(fns, block_or_read):
    """Worker-side: resolve a read marker, then run the fused stage chain."""
    if isinstance(block_or_read, tuple) and len(block_or_read) == 3 \
            and block_or_read[0] == "__read__":
        _tag, loader, path = block_or_read
        block = loader(path)
    else:
        block = block_or_read
    for fn in fns:
        block = fn(block)
    return block


def fetch(block_or_ref) -> Block:
    import ray_tpu
    if isinstance(block_or_ref, ray_tpu.ObjectRef):
        return ray_tpu.get(block_or_ref)
    if isinstance(block_or_ref, tuple) and len(block_or_ref) == 3 \
            and block_or_ref[0] == "__read__":
        return _apply_chain([], block_or_ref)
    return block_or_ref


def execute(ds) -> List[Any]:
    """Run the dataset's plan; returns a list of blocks/ObjectRefs."""
    import ray_tpu

    blocks: List[Any] = list(ds._source)
    stages = list(ds._stages)
    while stages:
        # Fuse the longest prefix of map-like stages.
        fused: List[Callable] = []
        while stages and stages[0].kind == "map":
            fused.append(stages.pop(0).fn)
        if fused or _has_read_markers(blocks):
            blocks = _run_fused(blocks, fused)
        if stages:
            barrier = stages.pop(0)
            blocks = _run_barrier(blocks, barrier)
    return blocks


def _has_read_markers(blocks: List[Any]) -> bool:
    return any(isinstance(b, tuple) and len(b) == 3 and b[0] == "__read__"
               for b in blocks)


def _run_fused(blocks: List[Any], fns: List[Callable]) -> List[Any]:
    import ray_tpu
    if not ray_tpu.is_initialized():
        # Local fallback: run inline (useful for pure-driver tests).
        return [_apply_chain(fns, fetch(b)) for b in blocks]

    apply_remote = ray_tpu.remote(_apply_chain)
    out: List[Any] = [None] * len(blocks)
    in_flight = {}
    idx = 0
    while idx < len(blocks) or in_flight:
        while idx < len(blocks) and len(in_flight) < MAX_IN_FLIGHT:
            ref = apply_remote.remote(fns, blocks[idx])
            in_flight[ref] = idx
            idx += 1
        if in_flight:
            done, _ = ray_tpu.wait(list(in_flight.keys()), num_returns=1,
                                   timeout=60)
            for ref in done:
                out[in_flight.pop(ref)] = ref
    return out


def _run_barrier(blocks: List[Any], stage) -> List[Any]:
    kind = stage.kind
    materialized = [fetch(b) for b in blocks]
    full = BlockAccessor.concat(materialized)
    n_rows = BlockAccessor(full).num_rows()
    if kind.startswith("shuffle"):
        seed = kind.split(":", 1)[1]
        rng = np.random.default_rng(None if seed == "None" else int(seed))
        perm = rng.permutation(n_rows)
        full = BlockAccessor(full).take(perm)
        n_out = max(1, len(blocks))
    elif kind.startswith("repartition"):
        n_out = int(kind.split(":", 1)[1])
    else:
        raise ValueError(f"unknown barrier stage {kind}")
    bounds = np.linspace(0, n_rows, n_out + 1, dtype=np.int64)
    return [BlockAccessor(full).slice(int(a), int(b))
            for a, b in zip(bounds[:-1], bounds[1:])]
