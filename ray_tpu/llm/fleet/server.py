"""FleetServer: N decode replicas behind one admission router.

The serving-fleet composition layer (reference analog: the reference's
multi-replica LLM serving deployments — vLLM engines behind a prefix-
aware request router with replica autoscaling):

* the SAME :class:`~ray_tpu.llm.disagg.AdmissionController` the single-
  engine plane uses fronts the whole fleet (per-class budgets, bounded
  queues, deadline shedding — one SLO surface regardless of replica
  count);
* a shared prefill TIER (:class:`~ray_tpu.llm.disagg.PrefillWorker`)
  computes prompt KV once and hands it to whichever replica the
  :class:`~ray_tpu.llm.fleet.router.FleetRouter` picks — through the
  shm object store when one is attached (zero-copy same-host; cross-
  host replicas ride the object store's p2p pull path instead, see
  :mod:`~ray_tpu.llm.fleet.remote`);
* full prefix hits skip the prefill tier entirely: the target replica
  replays its cached handoff straight into the decode batch;
* a manager thread runs health/drain bookkeeping, executes
  :class:`~ray_tpu.llm.fleet.autoscale.ServeAutoscalePolicy` decisions
  (scale up = spawn, scale down = drain-then-kill, never kill work),
  backfills replicas lost to chaos, and publishes a fleet snapshot to
  the cluster KV for the CLI/dashboard.

A replica loss sheds exactly the requests that were mid-flight on it —
retriable :class:`~ray_tpu.serve.OverloadError`-style results, never a
hang — and the fleet keeps serving on the survivors.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..._private import sanitizer
from ...serve.api import OverloadError
from ...util import telemetry, tracing
from ..engine import SamplingParams
from .autoscale import ServeAutoscalePolicy, ServeScaleConfig
from .prefix import full_hash, prefix_chain
from .replica import DecodeReplica
from .router import FleetRouter, RoutingConfig
from ..disagg.handoff import export_handoff, import_handoff
from ..disagg.prefill import PrefillWorker
from ..disagg.router import AdmissionConfig, AdmissionController, _Pending

#: Cluster-KV key prefix for published fleet snapshots (CLI/dashboard).
FLEET_KV_PREFIX = "serve:fleet:"


@dataclass
class FleetConfig:
    #: Initial replica count; also the backfill target until the
    #: autoscaler moves it.
    num_replicas: int = 1
    engine_options: Dict[str, Any] = field(default_factory=dict)
    #: Per-replica prefix-cache budget (host RAM for retained handoffs).
    cache_capacity_bytes: int = 64 * 1024 * 1024
    routing: Optional[RoutingConfig] = None
    #: None = fixed-size fleet (no autoscaler).
    autoscale: Optional[ServeScaleConfig] = None
    manager_interval_s: float = 0.25
    publish_interval_s: float = 0.5


class FleetServer:
    """Admission router + prefill tier + N decode replicas, one plane.

    Interface-compatible with :class:`~ray_tpu.llm.disagg.DisaggServer`
    (``submit``/``result``/``__call__``/``load``/``close``), so the
    open-loop loadgen and the serve deployment path drive it unchanged.
    """

    def __init__(self, build_params, *, name: str = "fleet",
                 admission: Optional[AdmissionConfig] = None,
                 config: Optional[FleetConfig] = None,
                 store=None, record_token_times: bool = False,
                 replica_factory: Optional[Callable[..., Any]] = None,
                 poll_interval_s: float = 0.002):
        self.name = name
        self.config = config or FleetConfig()
        params, cfg = build_params() if callable(build_params) \
            else build_params
        self._build = (params, cfg)
        eo = dict(self.config.engine_options)
        buckets = eo.get("prefill_buckets", (64, 256, 1024))
        self.prefill = PrefillWorker(
            params, cfg, prefill_buckets=buckets,
            page_size=eo.get("page_size", 16))
        self.admission = AdmissionController(admission or AdmissionConfig())
        self.router = FleetRouter(self.config.routing)
        self.policy = ServeAutoscalePolicy(self.config.autoscale) \
            if self.config.autoscale is not None else None
        self._store = store
        self._record_token_times = record_token_times
        self._block = eo.get("page_size", 16)
        self._factory = replica_factory or self._local_replica
        self._poll = poll_interval_s

        self._lock = threading.Lock()
        self._replicas: Dict[str, Any] = {}
        self._assigned: Dict[str, int] = {}
        self._draining: List[str] = []
        self._target = max(1, int(self.config.num_replicas))
        self._replica_ids = itertools.count()

        self._queue: "deque[_Pending]" = deque()
        self._events: Dict[int, threading.Event] = {}
        self._results: Dict[int, Dict[str, Any]] = {}
        self._meta: Dict[int, _Pending] = {}
        self._rid_map: Dict[tuple, int] = {}      # (replica, rid) -> pub
        self._pub_to_rid: Dict[int, tuple] = {}   # pub -> (replica, rid)
        self._outcome: Dict[int, tuple] = {}      # pub -> (outcome, replica)
        self._pub_ids = itertools.count(1)

        self._n_done = 0
        self._n_shed = 0
        self._prefix_counts = {"full": 0, "partial": 0, "miss": 0}
        self._rebalances = 0
        self._scales = {"up": 0, "down": 0}
        self._itl_buf: List[float] = []
        self._manager_errors = 0
        self._last_sweep = 0.0
        self._last_publish = 0.0

        self._stop = threading.Event()
        self._work = threading.Event()
        for _ in range(self._target):
            self._add_replica()
        self._dispatcher = sanitizer.spawn(
            self._dispatch_loop, name=f"fleet-dispatch-{name}")
        self._manager = sanitizer.spawn(
            self._manage_loop, name=f"fleet-manage-{name}")

    # -- replica set --------------------------------------------------------

    def _local_replica(self, name: str, on_finish) -> DecodeReplica:
        return DecodeReplica(
            self._build, name=name,
            engine_options=self.config.engine_options,
            cache_capacity_bytes=self.config.cache_capacity_bytes,
            record_token_times=self._record_token_times,
            on_finish=on_finish)

    def _add_replica(self) -> str:
        name = f"{self.name}-r{next(self._replica_ids)}"
        rep = self._factory(name, self._on_replica_finish)
        with self._lock:
            self._replicas[name] = rep
            self._assigned.setdefault(name, 0)
        self._set_count_gauge()
        self._work.set()
        return name

    def _set_count_gauge(self) -> None:
        with self._lock:
            n = sum(1 for r in self._replicas.values() if r.accepting)
        telemetry.set_gauge("ray_tpu_serve_replica_count", n,
                            tags={"fleet": self.name})

    def scale_up(self, reason: str = "manual") -> str:
        """Add one replica (autoscaler 'up', manual, or backfill)."""
        name = self._add_replica()
        with self._lock:
            # Count only accepting replicas: _replicas still holds any
            # draining ones, which must not inflate the fleet target.
            accepting = sum(
                1 for r in self._replicas.values() if r.accepting)
            self._target = max(self._target, accepting)
            self._scales["up"] += 1
        telemetry.inc("ray_tpu_serve_replica_scale_total",
                      tags={"direction": "up"})
        return name

    def scale_down(self, reason: str = "manual") -> Optional[str]:
        """Drain the least-loaded replica; the manager kills it once
        idle.  Never removes the last accepting replica."""
        with self._lock:
            accepting = [(n, r) for n, r in self._replicas.items()
                         if r.accepting]
            if len(accepting) <= 1:
                return None
            name, rep = min(
                accepting,
                key=lambda nr: len(nr[1].engine.running)
                + self._assigned.get(nr[0], 0))
            self._target = max(1, self._target - 1)
            self._draining.append(name)
            self._scales["down"] += 1
        rep.drain()
        telemetry.inc("ray_tpu_serve_replica_scale_total",
                      tags={"direction": "down"})
        self._set_count_gauge()
        return name

    def kill_replica(self, name: str, timeout_s: float = 5.0) -> bool:
        """Hard-kill one replica (chaos / lost node).  Its in-flight
        requests shed retriably; the manager backfills to target."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            self._assigned.pop(name, None)
            if name in self._draining:
                self._draining.remove(name)
        if rep is None:
            return False
        rep.kill(timeout_s)
        # Shed EVERY request still mapped to the replica (not just what
        # kill() reported: a remote actor lost to its node reports
        # nothing) — retriable shed, never a hang until caller timeout.
        with self._lock:
            lost_pubs = [(key, pub) for key, pub in self._rid_map.items()
                         if key[0] == name]
            for key, pub in lost_pubs:
                self._rid_map.pop(key, None)
                self._pub_to_rid.pop(pub, None)
            items = [self._meta.get(pub) for _k, pub in lost_pubs]
        for item in items:
            if item is not None:
                self._finish_shed(item, "replica_lost", dequeued=True)
        self._set_count_gauge()
        self._work.set()
        return True

    # -- intake (DisaggServer-compatible) -----------------------------------

    def _fleet_load(self) -> Dict[str, Any]:
        """Aggregate load for admission: the BEST accepting replica's
        view (the router places on the least loaded, so shedding keys
        off the replica a new request would actually land on)."""
        with self._lock:
            reps = [r for r in self._replicas.values() if r.accepting]
        if not reps:
            return {"kv_occupancy": 1.0, "waiting": 1}
        # Replica-level load_stats (NOT r.engine.load_stats): remote
        # replicas surface a cached snapshot; their .engine is a shim.
        stats = [r.load_stats() for r in reps]
        return {"kv_occupancy": min(s["kv_occupancy"] for s in stats),
                "waiting": min(s["waiting"] for s in stats)}

    def submit(self, body: Dict[str, Any],
               clazz: Optional[str] = None) -> int:
        if self._stop.is_set():
            raise RuntimeError("FleetServer is closed")
        clazz = clazz or str(body.get("class", "default"))
        prompt = list(body["prompt_tokens"])
        params = SamplingParams.from_body(body)
        if len(prompt) > self.prefill.prefill_buckets[-1]:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the largest "
                f"prefill bucket ({self.prefill.prefill_buckets[-1]})")
        total = len(prompt) + params.max_tokens
        if clazz not in self.admission.cfg.classes:
            clazz = "default"
        reason = self.admission.try_admit(
            clazz, total, self._fleet_load())
        if reason is not None:
            self.admission.note_shed(reason)
            with self._lock:
                self._n_shed += 1
            raise OverloadError(
                f"request shed ({reason}); retry with backoff")
        rc = self.admission.cfg.class_for(clazz)
        now = time.perf_counter()
        item = _Pending(next(self._pub_ids), prompt, params, clazz,
                        total, now, now + rc.queue_deadline_s,
                        abandon_deadline=now
                        + float(body.get("timeout_s", 300)) + 10.0)
        item.trace_parent = tracing.current()
        item.trace_root = tracing.new_child(item.trace_parent)
        item.t_submit_wall = time.time()
        ev = threading.Event()
        with self._lock:
            self._events[item.pub_id] = ev
            self._meta[item.pub_id] = item
            self._queue.append(item)
        self._work.set()
        return item.pub_id

    def result(self, pub_id: int, timeout_s: float = 300.0
               ) -> Dict[str, Any]:
        now = time.perf_counter()
        with self._lock:
            ev = self._events.get(pub_id)
            item = self._meta.get(pub_id)
            if item is not None:
                item.abandon_deadline = max(item.abandon_deadline,
                                            now + timeout_s + 10.0)
        if ev is None:
            raise KeyError(f"unknown or already-collected id {pub_id}")
        if not ev.wait(timeout_s):
            self._abandon(pub_id)
            return {"error": "generation timed out",
                    "finish_reason": "timeout"}
        with self._lock:
            res = self._results.pop(pub_id, None)
            self._events.pop(pub_id, None)
            self._meta.pop(pub_id, None)
            self._pub_to_rid.pop(pub_id, None)
        if res is None:
            return {"error": "request was cancelled",
                    "finish_reason": "cancelled"}
        return res

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        pub_id = self.submit(body)
        return self.result(pub_id,
                           timeout_s=float(body.get("timeout_s", 300)))

    # -- bookkeeping shared with DisaggServer's shape -----------------------

    def _trace_phase(self, item: _Pending, name: str, start_wall: float,
                     attrs: Optional[Dict[str, Any]] = None) -> None:
        if item.trace_root is None:
            return
        tracing.record_span(item.trace_root, name, start_wall,
                            time.time(), attrs or {})

    def _release_budget(self, item: Optional[_Pending]) -> None:
        if item is None:
            return
        with self._lock:
            if item.released:
                return
            item.released = True
        self.admission.note_finished(item.clazz, item.total_tokens)

    def _abandon(self, pub_id: int) -> None:
        with self._lock:
            ev = self._events.pop(pub_id, None)
            self._results.pop(pub_id, None)
            item = self._meta.pop(pub_id, None)
            target = self._pub_to_rid.pop(pub_id, None)
            if target is not None:
                self._rid_map.pop(target, None)
            self._outcome.pop(pub_id, None)
            try:
                self._queue.remove(item)
                queued = True
            except ValueError:
                queued = False
            rep = self._replicas.get(target[0]) \
                if target is not None else None
        if item is not None:
            if queued:
                self.admission.note_dequeued(item.clazz)
            self._release_budget(item)
        if rep is not None:
            rep.cancel(target[1])
        if ev is not None:
            ev.set()

    def _sweep_abandoned(self) -> None:
        now = time.perf_counter()
        if now - self._last_sweep < 0.5:
            return
        self._last_sweep = now
        with self._lock:
            stale = [pub_id for pub_id, item in self._meta.items()
                     if now > item.abandon_deadline]
        for pub_id in stale:
            self._abandon(pub_id)

    def _gone(self, item: _Pending) -> bool:
        with self._lock:
            return item.pub_id not in self._meta

    def _finish_shed(self, item: _Pending, reason: str,
                     dequeued: bool = False) -> None:
        if not dequeued:
            self.admission.note_dequeued(item.clazz)
        self._release_budget(item)
        self.admission.note_shed(reason)
        with self._lock:
            self._n_shed += 1
        self._publish(item.pub_id,
                      {"error": f"request shed ({reason}); retry with "
                                "backoff",
                       "reason": reason, "retriable": True,
                       "finish_reason": "shed"})

    def _publish(self, pub_id: int, result: Dict[str, Any]) -> None:
        with self._lock:
            ev = self._events.get(pub_id)
            item = self._meta.get(pub_id)
            if ev is None:
                self._meta.pop(pub_id, None)
                self._pub_to_rid.pop(pub_id, None)
                self._outcome.pop(pub_id, None)
                return
            self._results[pub_id] = result
        if item is not None and item.trace_root is not None:
            tracing.record_span(
                item.trace_parent, "llm_request", item.t_submit_wall,
                time.time(),
                {"mode": "fleet", "class": item.clazz,
                 "finish_reason": result.get("finish_reason")},
                ctx=item.trace_root)
        ev.set()

    # -- dispatch (router queue -> a replica) -------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            item = None
            with self._lock:
                if self._queue:
                    item = self._queue.popleft()
            if item is None:
                self._work.wait(0.02)
                self._work.clear()
                continue
            self._trace_phase(item, "queue_wait", item.t_submit_wall,
                              {"class": item.clazz})
            now = time.perf_counter()
            self.admission.note_queue_wait(now - item.t_submit)
            if now > item.deadline:
                self._finish_shed(item, "deadline")
                continue
            try:
                self._dispatch(item)
            except Exception as e:  # publish, never wedge the loop
                self.admission.note_dequeued(item.clazz)
                self._release_budget(item)
                self._publish(item.pub_id,
                              {"error": str(e), "finish_reason": "error"})

    def _views(self) -> List[Dict[str, Any]]:
        """Routing snapshot: one view per ACCEPTING replica."""
        with self._lock:
            reps = [(n, r) for n, r in self._replicas.items()
                    if r.accepting]
            assigned = dict(self._assigned)
        return [{"name": n, "load": r.load_stats(),
                 "summary": r.summary(),
                 "assigned": assigned.get(n, 0)} for n, r in reps]

    def _map(self, item: _Pending, replica: str, rid: int,
             outcome: str, rep) -> None:
        """Register a dispatched request's (replica, rid) — unless the
        caller abandoned it during the hand-off, or a chaos kill landed
        between the import and this registration (the kill's shed sweep
        can't see an unregistered rid, so the request would hang until
        caller timeout — shed it here instead)."""
        with self._lock:
            alive = item.pub_id in self._meta
            routed = self._replicas.get(replica) is rep
            if alive and routed:
                self._rid_map[(replica, rid)] = item.pub_id
                self._pub_to_rid[item.pub_id] = (replica, rid)
                self._outcome[item.pub_id] = (outcome, replica)
                self._prefix_counts[outcome] = \
                    self._prefix_counts.get(outcome, 0) + 1
        if not alive:
            rep.cancel(rid)
        elif not routed:
            self._finish_shed(item, "replica_lost")
            return
        else:
            # Count only successfully mapped dispatches so the series
            # stays in lockstep with status()'s _prefix_counts.
            telemetry.inc("ray_tpu_serve_prefix_hit_total",
                          tags={"outcome": outcome})
        self.admission.note_dequeued(item.clazz)
        self._work.set()

    def _dispatch(self, item: _Pending) -> None:
        """Route one admitted request: score replicas, try the cache-hit
        fast path, else prefill once and import onto the chosen replica
        — re-routing (same handoff, no re-prefill) whenever the target
        stops accepting mid-retry (drain, chaos kill)."""
        params = item.params
        chain = prefix_chain(item.prompt, self._block)
        fh = full_hash(item.prompt)
        handoff = None
        keepalive = None
        oid = None
        rebalance_seen = False
        try:
            while not self._stop.is_set():
                if self._gone(item):
                    self.admission.note_dequeued(item.clazz)
                    return
                if time.perf_counter() > item.deadline:
                    self._finish_shed(item, "deadline")
                    return
                views = self._views()
                if not views:
                    time.sleep(self._poll)
                    continue
                decision = self.router.route(views, chain, fh)
                with self._lock:
                    rep = self._replicas.get(decision.replica)
                if rep is None or not rep.accepting:
                    continue
                if decision.rebalanced and not rebalance_seen:
                    rebalance_seen = True
                    with self._lock:
                        self._rebalances += 1
                    telemetry.inc("ray_tpu_serve_rebalance_total")
                if handoff is None and decision.outcome == "full" \
                        and params.temperature <= 0.0:
                    rid = rep.try_serve_cached(
                        item.prompt, params, item.t_submit)
                    if rid is not None:
                        self._trace_phase(
                            item, "prefix_replay", time.time(),
                            {"replica": decision.replica,
                             "shared_blocks": decision.shared_blocks})
                        self._map(item, decision.replica, rid, "full",
                                  rep)
                        return
                    # Cache raced away (eviction) or momentary engine
                    # backpressure: fall through to the cold path.
                if handoff is None:
                    t_pf = time.time()
                    handoff = self.prefill.prefill(
                        item.prompt, params, t_submit=item.t_submit)
                    self._trace_phase(item, "prefill", t_pf,
                                      {"prompt_tokens": len(item.prompt)})
                    if self._store is not None:
                        from ..._private.ids import ObjectID
                        oid = ObjectID.from_random()
                        desc = export_handoff(self._store, oid, handoff)
                        if desc is not None:
                            handoff, keepalive = import_handoff(desc)
                        else:
                            oid = None  # store full: direct handoff
                # Bounded import retries on THIS target, then re-route:
                # a draining/killed target must not eat the deadline.
                outcome = "miss" if decision.outcome == "full" \
                    else decision.outcome
                with self._lock:
                    self._assigned[decision.replica] = \
                        self._assigned.get(decision.replica, 0) + 1
                rid = None
                retarget_at = time.perf_counter() + 0.05
                try:
                    t_adm = time.time()
                    while not self._stop.is_set():
                        if not rep.accepting or self._gone(item):
                            break
                        rid = rep.import_prefill(handoff)
                        if rid is not None:
                            break
                        if time.perf_counter() > min(item.deadline,
                                                     retarget_at):
                            break
                        time.sleep(self._poll)
                finally:
                    with self._lock:
                        if decision.replica in self._assigned:
                            self._assigned[decision.replica] = max(
                                0, self._assigned[decision.replica] - 1)
                if rid is not None:
                    self._trace_phase(item, "decode_admission", t_adm,
                                      {"replica": decision.replica,
                                       "engine_rid": rid})
                    self._map(item, decision.replica, rid, outcome, rep)
                    return
                # else: loop re-evaluates (deadline, gone, re-route).
            self._finish_shed(item, "deadline")
        finally:
            # import_prefill copies pages device-ward (and the cache
            # retains its own host copy), so the staged blob can go.
            del keepalive
            if oid is not None:
                from ..._private.object_store import release_page_blob
                release_page_blob(self._store, oid)

    # -- replica finish callback (runs on replica drive threads) ------------

    def _on_replica_finish(self, replica, req) -> None:
        with self._lock:
            pub_id = self._rid_map.pop((replica.name, req.request_id),
                                       None)
            item = self._meta.get(pub_id) if pub_id is not None else None
            outcome, rep_name = self._outcome.pop(
                pub_id, (None, replica.name)) if pub_id is not None \
                else (None, replica.name)
        if pub_id is None:
            return
        self._release_budget(item)
        itl = [b - a for a, b in zip(req.token_times,
                                     req.token_times[1:])]
        with self._lock:
            self._n_done += 1
            if itl:
                self._itl_buf.extend(itl)
                del self._itl_buf[:-4096]
        self._publish(pub_id, {
            "output_tokens": list(req.output_tokens),
            "finish_reason": req.finish_reason,
            "ttft_s": (req.t_first - req.t_submit)
            if req.t_first and req.t_submit else None,
            "itl_s": itl,
            "replica": rep_name,
            "prefix_outcome": outcome,
        })

    # -- manager (health / drain / autoscale / publish) ---------------------

    def _manage_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.config.manager_interval_s)
            if self._stop.is_set():
                return
            try:
                self._manage_tick()
            except Exception:
                # Never kill the manager: a transient spawn/publish
                # failure must not strand draining replicas forever.
                self._manager_errors += 1

    def _manage_tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._sweep_abandoned()
        # Reap replicas that died out from under us (a remote actor's
        # node went away): shed their in-flight, let backfill replace.
        with self._lock:
            dead = [n for n, r in self._replicas.items()
                    if r.state == "dead"]
        for name in dead:
            self.kill_replica(name)
        # Finish drains whose replicas went idle.
        with self._lock:
            draining = [(n, self._replicas.get(n))
                        for n in list(self._draining)]
        for name, rep in draining:
            if rep is None:
                with self._lock:
                    if name in self._draining:
                        self._draining.remove(name)
                continue
            if rep.idle():
                with self._lock:
                    if name in self._draining:
                        self._draining.remove(name)
                    self._replicas.pop(name, None)
                    self._assigned.pop(name, None)
                rep.kill()
                self._set_count_gauge()
        # Backfill chaos losses up to target (autoscaler moves target).
        with self._lock:
            active = sum(1 for r in self._replicas.values()
                         if r.accepting)
            deficit = self._target - active
            pending = len(self._draining)
        if deficit > 0 and not pending:
            self.scale_up(reason="backfill")
            active += 1
        # Autoscale.
        if self.policy is not None:
            with self._lock:
                samples = list(self._itl_buf)
                self._itl_buf.clear()
                n_shed, n_done = self._n_shed, self._n_done
                assigned_total = sum(self._assigned.values())
            self.policy.observe(
                queue_depth=self.admission.queue_depth()
                + assigned_total,
                shed_total=n_shed, completed_total=n_done,
                replicas=active, itl_samples=samples, now=now)
            decision = self.policy.decide(pending=pending, now=now)
            if decision is not None:
                if decision.direction == "up":
                    with self._lock:
                        self._target += 1
                    self.scale_up(reason=decision.reason)
                else:
                    if self.scale_down(reason=decision.reason) is None:
                        self.policy.forget_action()
        self._publish_status()

    # -- status surfaces ----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            reps = list(self._replicas.items())
            assigned = dict(self._assigned)
            snap = {
                "target_replicas": self._target,
                "draining": list(self._draining),
                "completed": self._n_done,
                "shed": self._n_shed,
                "prefix": dict(self._prefix_counts),
                "rebalances": self._rebalances,
                "scales": dict(self._scales),
            }
        replicas = []
        for name, rep in reps:
            stats = rep.load_stats()
            stats["assigned"] = assigned.get(name, 0)
            replicas.append(stats)
        snap["name"] = self.name
        snap["replicas"] = replicas
        snap["router_queue"] = self.admission.queue_depth()
        snap["autoscale"] = self.policy.status() \
            if self.policy is not None else None
        return snap

    def load(self) -> Dict[str, Any]:
        stats = self._fleet_load()
        stats["router_queue"] = self.admission.queue_depth()
        stats["mode"] = "fleet"
        with self._lock:
            stats["replicas"] = len(self._replicas)
        return stats

    def _publish_status(self) -> None:
        """Throttled fleet snapshot into the cluster KV (a no-op when
        no cluster/controller is up — bench and unit runs)."""
        now = time.monotonic()
        if now - self._last_publish < self.config.publish_interval_s:
            return
        self._last_publish = now
        try:
            from ..._private.api import _control
            _control("kv_put", FLEET_KV_PREFIX + self.name,
                     json.dumps(self.status(), default=str).encode())
        except Exception:
            pass

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout_s: float = 5.0) -> None:
        """Bounded shutdown: stop dispatcher/manager, kill replicas,
        fail every still-pending request loudly."""
        self._stop.set()
        self._work.set()
        self._dispatcher.join(timeout_s)
        self._manager.join(timeout_s)
        with self._lock:
            reps = list(self._replicas.values())
            self._replicas.clear()
            self._assigned.clear()
        for rep in reps:
            try:
                rep.kill(timeout_s)
            except Exception:
                pass
        try:
            from ..._private.api import _control
            _control("kv_del", FLEET_KV_PREFIX + self.name)
        except Exception:
            pass
        with self._lock:
            for pub_id, ev in list(self._events.items()):
                if pub_id not in self._results:
                    self._results[pub_id] = {"error": "server closed",
                                             "finish_reason": "closed"}
                ev.set()

    shutdown = close
