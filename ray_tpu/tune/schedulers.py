"""Trial schedulers: FIFO, ASHA, median-stopping, HyperBand, PBT.

Reference analog: python/ray/tune/schedulers/ (async_hyperband.py
ASHAScheduler, hyperband.py HyperBandScheduler, median_stopping_rule.py,
pbt.py PopulationBasedTraining).  The controller calls
``on_result(trial_id, step, value)`` for every intermediate report; CONTINUE
or STOP comes back.  PBT additionally exposes ``take_restart(trial_id)``:
after a STOP the tuner asks whether the trial should be relaunched with an
exploited config + checkpoint (the pause/exploit/explore cycle).
"""

from __future__ import annotations

import collections
import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, step: int, value: float) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rungs at grace_period * reduction_factor**k; a trial reaching a rung
    stops unless its metric is in the top 1/reduction_factor of completed
    rung entries.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = collections.defaultdict(list)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self.grace
        while t < self.max_t:
            levels.append(t)
            t *= self.rf
        return levels

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        if self.mode == "max":
            value = -value
        for rung in self._rung_levels():
            if step == rung:
                peers = self._rungs[rung]
                peers.append(value)
                k = max(1, len(peers) // self.rf)
                cutoff = sorted(peers)[k - 1]
                if value > cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose running-best is worse than the median of other
    trials' running means (reference: median_stopping_rule.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        if self.mode == "max":
            value = -value
        self._history[trial_id].append(value)
        if step < self.grace:
            return CONTINUE
        others = [sum(v) / len(v) for t, v in self._history.items()
                  if t != trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        others_sorted = sorted(others)
        median = others_sorted[len(others_sorted) // 2]
        best = min(self._history[trial_id])
        return STOP if best > median else CONTINUE


class HyperBandScheduler:
    """HyperBand (reference: tune/schedulers/hyperband.py): multiple
    successive-halving brackets trading off number of configurations vs
    budget per configuration.  Trials are assigned to brackets round-robin
    at first report; within a bracket, a trial reaching its current rung
    stops unless in the top 1/eta of that rung's completed entries (the
    asynchronous rung rule, so stragglers never block a bracket)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 81, eta: int = 3):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = eta
        # Bracket k starts its first rung at eta**k steps (integer loop:
        # float log truncation would drop the final bracket for exact
        # powers of eta).
        self._brackets: List[List[int]] = []
        start = 1
        while start <= max_t:
            rungs = []
            t = start
            while t < max_t:
                rungs.append(t)
                t *= eta
            self._brackets.append(rungs or [max_t])
            start *= eta
        self._trial_bracket: Dict[str, int] = {}
        self._next_bracket = 0
        self._rungs: Dict[Tuple[int, int], List[float]] = \
            collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        if self.mode == "max":
            value = -value
        b = self._trial_bracket.get(trial_id)
        if b is None:
            b = self._next_bracket % len(self._brackets)
            self._next_bracket += 1
            self._trial_bracket[trial_id] = b
        for rung in self._brackets[b]:
            if step == rung:
                peers = self._rungs[(b, rung)]
                peers.append(value)
                k = max(1, len(peers) // self.eta)
                cutoff = sorted(peers)[k - 1]
                if value > cutoff:
                    return STOP
        return CONTINUE


class PopulationBasedTraining:
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` steps, trials in the bottom quantile stop
    and restart from a top-quantile trial's checkpoint with mutated
    hyperparameters (exploit + explore).

    ``hyperparam_mutations``: {name: list-of-choices | callable() | (lo, hi)}.
    The tuner drives the restart: after a STOP it calls
    ``take_restart(trial_id)`` and, when a directive comes back, relaunches
    the trial with the new config seeded from the source checkpoint.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int = 0):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._latest: Dict[str, float] = {}
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._restarts: Dict[str, Tuple[Dict[str, Any], str]] = {}

    def register_trial(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for name, spec in self.mutations.items():
            if self._rng.random() < self.resample_p or name not in out:
                out[name] = self._sample(spec)
            elif isinstance(spec, list):
                # Categorical: step to an adjacent allowed value
                # (reference pbt.py behavior) — never off-menu products.
                try:
                    i = spec.index(out[name])
                    j = max(0, min(len(spec) - 1,
                                   i + self._rng.choice([-1, 1])))
                    out[name] = spec[j]
                except ValueError:
                    out[name] = self._sample(spec)
            elif isinstance(out[name], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                v = out[name] * factor
                if isinstance(out[name], int):
                    v = max(int(v), 1) if out[name] >= 1 else int(v)
                out[name] = type(out[name])(v)
            else:
                out[name] = self._sample(spec)
        return out

    def _sample(self, spec):
        """callable -> call it; 2-number tuple -> uniform range;
        list/other iterable -> categorical choice."""
        if callable(spec):
            return spec()
        if isinstance(spec, tuple) and len(spec) == 2 and all(
                isinstance(x, (int, float)) for x in spec):
            lo, hi = spec
            return self._rng.uniform(lo, hi)
        return self._rng.choice(list(spec))

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        signed = -value if self.mode == "max" else value
        self._latest[trial_id] = signed
        if step % self.interval != 0 or len(self._latest) < 2:
            return CONTINUE
        ordered = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ordered)
        k = max(1, int(n * self.quantile))
        top = [t for t, _ in ordered[:k]]
        bottom = {t for t, _ in ordered[-k:]}
        if trial_id in bottom and trial_id not in top:
            source = self._rng.choice(top)
            new_config = self._mutate(self._configs.get(source, {}))
            self._restarts[trial_id] = (new_config, source)
            return STOP
        return CONTINUE

    def take_restart(self, trial_id: str
                     ) -> Optional[Tuple[Dict[str, Any], str]]:
        """(new_config, source_trial_id) when this STOP was an exploit."""
        return self._restarts.pop(trial_id, None)
