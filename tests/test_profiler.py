"""Cluster profiler: on-demand merged capture, recompile detection,
step-phase attribution, span nesting, bench --compare gate.

Reference analogs: the reference dashboard's py-spy/`ray timeline`
integration and the OpenTelemetry substrate its native layer ships —
here the TPU-native equivalents built in PR 10 (ISSUE 10).
"""

import json
import os
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import profiler
from ray_tpu.profiler import attribution, recompile
from ray_tpu.util import state as state_api
from ray_tpu.util import telemetry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(predicate, timeout=15.0, period=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(period)
    return predicate()


@ray_tpu.remote
def profiler_probe(flag_path, marker_path):
    open(marker_path, "w").close()
    while not os.path.exists(flag_path):
        sum(i * i for i in range(2000))
    return "done"


class TestLiveCapture:
    def test_two_worker_merged_trace(self, ray_start, tmp_path):
        """Acceptance: a capture on a >=2-worker cluster produces ONE
        merged Chrome-trace JSON whose sample events span both workers
        AND the driver on a common (driver) clock."""
        flag = str(tmp_path / "release")
        markers = [str(tmp_path / f"m{i}") for i in range(2)]
        refs = [profiler_probe.remote(flag, m) for m in markers]
        assert _wait_for(
            lambda: all(os.path.exists(m) for m in markers), 30), \
            "probe tasks never started"
        t0 = time.time()
        try:
            out = state_api.profile(duration_s=1.0)
        finally:
            open(flag, "w").close()
        t1 = time.time()
        assert ray_tpu.get(refs, timeout=60) == ["done", "done"]

        assert out["unresponsive"] == []
        assert len(out["workers"]) >= 2
        # The merged trace landed on disk (atomic publish) and is the
        # same document returned inline.
        assert os.path.isfile(out["path"])
        with open(out["path"]) as f:
            on_disk = json.load(f)
        doc = out["trace"]
        assert on_disk["otherData"]["profile_id"] == \
            doc["otherData"]["profile_id"]

        samples = [e for e in doc["traceEvents"]
                   if e.get("ph") == "X" and e.get("cat") == "sample"]
        pids = {e["pid"] for e in samples}
        worker_pids = {p for p in pids if str(p).startswith("worker:")}
        assert len(worker_pids) >= 2, pids
        assert any(str(p).startswith("driver") for p in pids), pids
        # The busy probe function is visible in the sampled slices.
        assert any("profiler_probe" in str(e.get("name", ""))
                   or any("profiler_probe" in fr for fr in
                          e.get("args", {}).get("stack", ()))
                   for e in samples)

        # Clock alignment: every sample slice sits inside the capture
        # window IN DRIVER TIME (worker events were shifted by their
        # reported clock offset), and per-process offsets are sane for
        # a same-host cluster.
        lo, hi = (t0 - 2.0) * 1e6, (t1 + 2.0) * 1e6
        for e in samples:
            assert lo <= e["ts"] <= hi, e
        procs = [p for p in doc["otherData"]["processes"]
                 if not p.get("error")]
        assert len(procs) >= 3  # driver + 2 workers
        for p in procs:
            assert abs(p["clock_offset_s"]) < 5.0, p
            assert p["num_samples"] > 5, p

    def test_profile_from_inside_a_task(self, ray_start):
        """The ctl verb is blocking-listed: calling it from a worker
        must not deadlock the poller thread that routes the replies."""
        @ray_tpu.remote
        def nested():
            from ray_tpu import profiler as prof
            out = prof.profile(duration_s=0.3)
            return len(out["workers"])

        # At least the calling worker itself captured.
        assert ray_tpu.get(nested.remote(), timeout=120) >= 1

    def test_bundle_attaches_profile(self, ray_start):
        """Flight-recorder bundles attach the merged profile trace when
        asked (the watchdog's bundle_profile_s knob rides this)."""
        path = ray_start.ctl_debug_dump("profiler_unit",
                                        capture_stacks=False,
                                        profile_s=0.3)
        trace_path = os.path.join(path, "profile_trace.json")
        assert os.path.isfile(trace_path)
        with open(trace_path) as f:
            doc = json.load(f)
        assert doc["traceEvents"], "bundle profile has no events"
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert "profile_trace.json" in manifest["contents"]


class TestRestSurface:
    def test_job_server_profile_endpoint(self, ray_start):
        """POST /api/cluster/profile (the `ray-tpu profile` transport)
        returns the merged trace + summary."""
        from ray_tpu.job_submission import JobSubmissionClient
        from ray_tpu.job_submission.manager import JobManager
        from ray_tpu.job_submission.server import JobServer
        server = JobServer(JobManager(), port=0)
        try:
            client = JobSubmissionClient(server.address)
            out = client._request(
                "POST", "/api/cluster/profile?duration_s=0.3")
            assert "traceEvents" in out["trace"]
            assert out["num_events"] == len(out["trace"]["traceEvents"])
            slim = client._request(
                "POST",
                "/api/cluster/profile?duration_s=0.2&include_trace=0")
            assert "trace" not in slim and "path" in slim
        finally:
            server.stop()


class TestRecompileDetector:
    def setup_method(self):
        recompile._reset_for_tests()

    def teardown_method(self):
        recompile._reset_for_tests()

    def test_shape_churn_flagged_post_warmup(self, caplog):
        """Acceptance: an injected post-warmup shape change is flagged,
        naming the offending shapes/dtypes."""
        import jax
        import jax.numpy as jnp
        fn = profiler.track(jax.jit(lambda x: x * 2), name="churny")
        with caplog.at_level("WARNING", logger="ray_tpu.profiler"):
            fn(jnp.ones((4,), jnp.float32))   # compile 1 (warmup)
            fn(jnp.ones((4,), jnp.float32))   # cache hit -> warm
            assert not caplog.records
            fn(jnp.ones((8,), jnp.float32))   # post-warmup churn
        rep = recompile.report()["churny"]
        assert rep["warm"] is True
        assert rep["compiles"] >= 2
        assert rep["recompiles"] == 1
        assert "(float32[4])" in rep["signatures"]
        assert "(float32[8])" in rep["signatures"]
        warnings = [r for r in caplog.records
                    if "post-warmup recompilation" in r.message]
        assert len(warnings) == 1
        msg = warnings[0].getMessage()
        # The warning names BOTH the new and the previously-seen shapes.
        assert "float32[8]" in msg and "float32[4]" in msg
        assert "churny" in msg

    def test_warns_once_but_counts_every_recompile(self, caplog):
        import jax
        import jax.numpy as jnp
        fn = profiler.track(jax.jit(lambda x: x + 1), name="churny2")
        with caplog.at_level("WARNING", logger="ray_tpu.profiler"):
            fn(jnp.ones((2,)))
            fn(jnp.ones((2,)))
            fn(jnp.ones((3,)))
            fn(jnp.ones((5,)))
        rep = recompile.report()["churny2"]
        assert rep["recompiles"] == 2
        assert sum("post-warmup recompilation" in r.message
                   for r in caplog.records) == 1

    def test_pre_warmup_bucket_sweep_is_not_churn(self):
        """Compiling several shapes BEFORE any cache hit (bucketed
        prefill warmup, multi-shape eval) is not a recompile verdict."""
        import jax
        import jax.numpy as jnp
        fn = profiler.track(jax.jit(lambda x: x.sum()), name="buckets")
        for n in (2, 4, 8):
            fn(jnp.ones((n,)))
        rep = recompile.report()["buckets"]
        assert rep["recompiles"] == 0 and not rep["warm"]

    def test_install_patches_and_uninstall_restores_jit(self):
        import jax
        orig = jax.jit
        try:
            assert recompile.install() is True
            assert jax.jit is not orig

            @jax.jit
            def auto_tracked(x):
                return x - 1
            import jax.numpy as jnp
            auto_tracked(jnp.ones((3,)))
            assert "auto_tracked" in recompile.report()
            # AOT surface forwards through the wrapper.
            assert hasattr(auto_tracked, "lower")
        finally:
            recompile.uninstall()
        assert jax.jit is orig


class TestStepPhases:
    def setup_method(self):
        attribution._reset_for_tests()

    def test_phases_sum_to_elapsed_property(self):
        """Property: attributed phases never exceed the elapsed window,
        and finalize's derived 'other' makes them sum EXACTLY to the
        step time."""
        t0 = time.monotonic()
        with attribution.step_phase("data_wait"):
            time.sleep(0.03)
        with attribution.step_phase("compute"):
            time.sleep(0.02)
            with attribution.step_phase("collective"):
                time.sleep(0.02)
        elapsed = time.monotonic() - t0
        phases = attribution.pop_phases()
        assert attribution.pop_phases() == {}  # popped = cleared
        assert sum(phases.values()) <= elapsed + 0.005
        # Nested time is charged to the INNER phase only.
        assert 0.015 <= phases["compute"] <= 0.04
        assert 0.015 <= phases["collective"] <= 0.04
        step_s = elapsed + 0.05  # pretend the step had untracked tail
        final = attribution.finalize_step_phases(phases, step_s,
                                                 ckpt_s=0.01)
        assert abs(sum(final.values()) - step_s) < 1e-9 \
            or final["other"] == 0.0
        assert final["ckpt_block"] == pytest.approx(0.01)

    def test_fence_returns_value(self):
        import jax.numpy as jnp
        x = jnp.ones((4,))
        assert attribution.fence(x) is x
        assert attribution.fence({"a": 1})["a"] == 1

    def test_e2e_trainer_attribution(self, ray_start, tmp_path):
        """Acceptance: a real fit() decomposes every step; per-report
        phases (incl. the derived 'other') sum to the report-to-report
        interval, Result.step_phases summarizes them, and the goodput
        tracker books data-wait out of the productive phase."""
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def fn(config):
            import time as _t

            import ray_tpu.train as train
            for _ in range(4):
                with train.step_phase("data_wait"):
                    _t.sleep(0.05)
                with train.step_phase("compute"):
                    _t.sleep(0.03)
                train.report({"loss": 1.0})

        res = JaxTrainer(
            fn, train_loop_config={},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="profiler_phases",
                                 storage_path=str(tmp_path))).fit()
        assert res.error is None
        sp = res.step_phases
        assert sp is not None
        assert sp["seconds"]["data_wait"] >= 0.15
        assert sp["seconds"]["compute"] >= 0.09
        assert sum(sp["fraction"].values()) == pytest.approx(1.0, abs=0.02)

        # Per-report property: phases sum to the step interval (mono
        # report-to-report delta), within scheduler tolerance.
        rank0 = sorted((r for r in res.all_reports if r["rank"] == 0),
                       key=lambda r: r["seq"])
        assert len(rank0) == 4
        for prev, cur in zip(rank0, rank0[1:]):
            if prev["incarnation"] != cur["incarnation"]:
                continue
            step_s = cur["mono"] - prev["mono"]
            assert "other" in cur["phases"]
            assert sum(cur["phases"].values()) == \
                pytest.approx(step_s, abs=0.05)

        # Goodput learned the data-wait idle attribution.
        assert res.goodput["phases_s"].get("data_wait", 0.0) >= 0.1
        # And the catalog histogram carries per-phase observations.
        from ray_tpu.util.metrics import prometheus_text
        text = prometheus_text()
        assert 'ray_tpu_train_step_phase_seconds_count' \
            '{phase="data_wait"}' in text


class TestSpanNesting:
    """Satellite regression: profile_span is re-entrant with parent
    linkage — an inner span's duration is no longer attributed to both
    levels (extra.self_s excludes children)."""

    def _capture_spans(self, body):
        spans = []
        orig = telemetry._emit_span

        def capture(name, category, start_s, end_s, extra=None):
            spans.append({"name": name, "start": start_s, "end": end_s,
                          "extra": extra or {}})
        telemetry._emit_span = capture
        try:
            body()
        finally:
            telemetry._emit_span = orig
        return {s["name"]: s for s in spans}

    def test_nested_spans_link_and_exclude_child_time(self):
        def body():
            with telemetry.profile_span("outer"):
                time.sleep(0.04)
                with telemetry.profile_span("inner"):
                    time.sleep(0.05)
        spans = self._capture_spans(body)
        outer, inner = spans["outer"], spans["inner"]
        assert inner["extra"]["parent_id"] == outer["extra"]["span_id"]
        assert outer["extra"]["parent_id"] is None
        outer_dur = outer["end"] - outer["start"]
        inner_dur = inner["end"] - inner["start"]
        # Inclusive duration still covers the child; SELF time doesn't.
        assert outer_dur >= inner_dur
        assert outer["extra"]["self_s"] == pytest.approx(
            outer_dur - inner_dur, abs=0.02)
        assert inner["extra"]["self_s"] == pytest.approx(inner_dur,
                                                         abs=0.02)

    def test_single_instance_reentrant(self):
        sp = telemetry.profile_span("re")

        def body():
            with sp:
                time.sleep(0.01)
                with sp:
                    time.sleep(0.01)
        spans = []
        orig = telemetry._emit_span
        telemetry._emit_span = \
            lambda n, c, s, e, extra=None: spans.append(extra)
        try:
            body()
        finally:
            telemetry._emit_span = orig
        assert len(spans) == 2
        inner, outer = spans  # inner exits first
        assert inner["parent_id"] == outer["span_id"]

    def test_state_profile_span_links_to_parent(self, ray_start):
        """state.profile_span shares the stack: nested user spans carry
        parent linkage all the way into the driver timeline."""
        with state_api.profile_span("outer_user"):
            with state_api.profile_span("inner_user"):
                time.sleep(0.01)
        trace = json.loads(ray_tpu.timeline())
        by_name = {}
        for ev in trace:
            if ev.get("name") in ("outer_user", "inner_user"):
                by_name[ev["name"]] = ev
        assert set(by_name) == {"outer_user", "inner_user"}
        outer_args = by_name["outer_user"]["args"]
        inner_args = by_name["inner_user"]["args"]
        assert inner_args["parent_id"] == outer_args["span_id"]
        assert "self_s" in outer_args


class TestCompareGate:
    def _bench(self):
        sys.path.insert(0, REPO_ROOT)
        import bench
        return bench

    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_regressions_detected_by_direction(self, tmp_path):
        bench = self._bench()
        a = self._write(tmp_path, "a.json", {
            "tps": 100.0, "itl_p99_ms": 10.0, "within_budget": True,
            "budget_pct": 2.0, "knobs": {"steps": 10}})
        b = self._write(tmp_path, "b.json", {
            "tps": 80.0, "itl_p99_ms": 13.0, "within_budget": False,
            "budget_pct": 4.0, "knobs": {"steps": 99}})
        out = bench.compare_bench(a, b, threshold=0.10)
        regressed = {r[0] for r in out["regressions"]}
        # Throughput down, latency up, health boolean flipped — and the
        # bookkeeping fields (budget, knobs) never gate.
        assert regressed == {"tps", "itl_p99_ms", "within_budget"}
        with pytest.raises(SystemExit):
            bench.run_compare(a, b, 0.10)

    def test_noise_below_threshold_passes(self, tmp_path):
        bench = self._bench()
        a = self._write(tmp_path, "a.json", {"tps": 100.0, "p99_ms": 10.0})
        b = self._write(tmp_path, "b.json", {"tps": 95.0, "p99_ms": 10.8})
        out = bench.compare_bench(a, b, threshold=0.10)
        assert not out["regressions"]

    def test_rep_lists_use_trimmed_mean(self, tmp_path):
        bench = self._bench()
        # One wild outlier rep in the candidate must not gate: the
        # trimmed mean drops best+worst before comparing.
        a = self._write(tmp_path, "a.json",
                        {"phases_on_s": [1.0, 1.0, 1.0, 1.0, 1.0]})
        b = self._write(tmp_path, "b.json",
                        {"phases_on_s": [1.0, 1.0, 1.02, 1.0, 9.0]})
        out = bench.compare_bench(a, b, threshold=0.10)
        assert not out["regressions"]

    def test_improvements_reported_not_fatal(self, tmp_path):
        bench = self._bench()
        a = self._write(tmp_path, "a.json", {"tokens_per_sec": 100.0})
        b = self._write(tmp_path, "b.json", {"tokens_per_sec": 150.0})
        out = bench.compare_bench(a, b, threshold=0.10)
        assert out["improvements"] and not out["regressions"]
        bench.run_compare(a, b, 0.10)  # exits 0


class TestRequestTrace:
    """Satellite: W3C trace context through the serve handle path and
    the disagg prefill->decode pipeline — one LLM request renders as a
    single trace tree with queue-wait / prefill / KV-transfer /
    decode-admission spans (TTFT is no longer one opaque histogram)."""

    def test_disagg_request_is_one_trace_tree(self, ray_start):
        import jax
        import jax.numpy as jnp

        from ray_tpu.llm.disagg import DisaggServer
        from ray_tpu.models import LlamaConfig
        from ray_tpu.models.llama import init_params
        from ray_tpu.util import tracing

        cfg = LlamaConfig(vocab_size=128, hidden=32, layers=2, heads=4,
                          kv_heads=2, head_dim=8, mlp_dim=64,
                          max_seq_len=128, attention_impl="reference",
                          remat=False, dtype=jnp.float32)
        params = init_params(cfg, jax.random.key(0))
        tracing.enable()
        srv = DisaggServer(
            lambda: (params, cfg), mode="disagg",
            engine_options={"max_slots": 2, "page_size": 8,
                            "num_pages": 64, "prefill_buckets": (16,)})
        try:
            out = srv({"prompt_tokens": [3, 17, 92, 5], "max_tokens": 4,
                       "timeout_s": 120})
            assert len(out["output_tokens"]) == 4
        finally:
            srv.close()
            tracing.disable()
        want = {"llm_request", "queue_wait", "prefill", "kv_transfer",
                "decode_admission"}
        match = None
        for tid in tracing.list_traces():
            spans = tracing.get_trace(tid)
            if "llm_request" in {s["name"] for s in spans}:
                match = spans
                break
        assert match is not None, "no llm_request trace recorded"
        names = {s["name"] for s in match}
        assert want <= names, names
        root = next(s for s in match if s["name"] == "llm_request")
        kids = {s["name"] for s in match
                if s.get("parent_span_id") == root["span_id"]}
        assert want - {"llm_request"} <= kids, kids
        # One trace id across the whole pipeline.
        assert len({s["trace_id"] for s in match}) == 1
        # Phase spans nest inside the root's window.
        for s in match:
            assert s["start_s"] >= root["start_s"] - 0.001
            assert s["end_s"] <= root["end_s"] + 0.001

    def test_tracing_span_context_manager(self, ray_start):
        """tracing.span: in-thread nesting installs/restores the current
        context — children inherit the trace id and parent linkage, and
        an error is stamped on the span."""
        from ray_tpu.util import tracing
        tracing.enable()
        prev = tracing.current()
        try:
            with tracing.span("outer_cm", {"k": "v"}):
                with tracing.span("inner_cm"):
                    time.sleep(0.01)
            assert tracing.current() is prev  # context restored
            with pytest.raises(ValueError):
                with tracing.span("boom_cm"):
                    raise ValueError("x")
        finally:
            tracing.disable()
        spans = [s for tid in tracing.list_traces()
                 for s in tracing.get_trace(tid)
                 if s["name"].endswith("_cm")]
        by_name = {s["name"]: s for s in spans}
        outer, inner = by_name["outer_cm"], by_name["inner_cm"]
        assert inner["parent_span_id"] == outer["span_id"]
        assert inner["trace_id"] == outer["trace_id"]
        assert outer["attributes"]["k"] == "v"
        assert inner["start_s"] >= outer["start_s"]
        assert by_name["boom_cm"]["attributes"]["error"] == "ValueError"

    def test_serve_handle_route_span_joins_request_trace(self, ray_start):
        from ray_tpu import serve
        from ray_tpu.util import tracing

        @serve.deployment(name="traced_echo")
        class _Echo:
            def __call__(self, body):
                return body

        tracing.enable()
        try:
            handle = serve.run(_Echo.bind())
            assert ray_tpu.get(handle.remote({"x": 1}),
                               timeout=60) == {"x": 1}
            route = _wait_for(lambda: [
                s for tid in tracing.list_traces()
                for s in tracing.get_trace(tid)
                if s["name"] == "serve_route traced_echo"])
            assert route, "no serve_route span recorded"
            trace = tracing.get_trace(route[0]["trace_id"])
            names = {s["name"] for s in trace}
            # The route span and the actor-method submit/execute spans
            # share ONE trace: the handle path extends the context.
            assert any(n.startswith("submit") for n in names), names
        finally:
            serve.shutdown()
            tracing.disable()


class TestCaptureUnits:
    def test_host_sampler_sees_named_thread(self):
        from ray_tpu.profiler.capture import capture_profile
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(500))
        t = threading.Thread(target=busy, name="unit-busy-thread")
        t.start()
        try:
            rec = capture_profile("unit", 0.4, hz=80,
                                  driver_wall_s=time.time())
        finally:
            stop.set()
            t.join()
        assert rec["error"] is None
        assert len(rec["samples"]) >= 10
        names = {th["name"] for s in rec["samples"]
                 for th in s["threads"].values()}
        assert "unit-busy-thread" in names
        assert abs(rec["clock_offset_s"]) < 1.0

    def test_concurrent_capture_reports_busy(self):
        from ray_tpu.profiler import capture as cap
        results = []

        def one(dur):
            results.append(cap.capture_profile("x", dur, hz=50))
        t = threading.Thread(target=one, args=(0.6,))
        t.start()
        time.sleep(0.1)
        one(0.1)
        t.join()
        errors = [r.get("error") for r in results]
        assert errors.count("capture already running") == 1

    def test_merge_is_deterministic_and_serializable(self):
        from ray_tpu.profiler.capture import capture_profile
        from ray_tpu.profiler.merge import merge_records
        rec = capture_profile("m", 0.2, hz=50, driver_wall_s=time.time())
        doc = merge_records([rec], meta={"profile_id": 7})
        json.dumps(doc)  # wire/disk safe
        assert doc["otherData"]["profile_id"] == 7
        assert doc["otherData"]["processes"][0]["num_samples"] == \
            len(rec["samples"])
