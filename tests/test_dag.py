"""Compiled graph tests (interpreted + compiled execution over channels).

Reference analogs: python/ray/dag/tests/experimental/test_accelerated_dag.py
(compile, execute, multi-output, error propagation, teardown) and
python/ray/tests/test_channel.py.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import (FLAG_DATA, FLAG_STOP, ChannelTimeoutError,
                                 ShmChannel)


@ray_tpu.remote
class Adder:
    def __init__(self, inc=1):
        self.inc = inc
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.inc

    def add2(self, a, b):
        return a + b

    def boom(self, x):
        raise ValueError("kapow")

    def num_calls(self):
        return self.calls


class TestShmChannel:
    def test_roundtrip(self):
        ch = ShmChannel(1024)
        ch.write(b"hello")
        flag, data = ch.read()
        assert flag == FLAG_DATA and data == b"hello"
        ch.write(b"", FLAG_STOP)
        flag, _ = ch.read()
        assert flag == FLAG_STOP
        ch.close()
        ch.unlink()

    def test_backpressure_and_timeout(self):
        ch = ShmChannel(64)
        ch.write(b"one")
        with pytest.raises(ChannelTimeoutError):
            ch.write(b"two", timeout=0.05)
        assert ch.read()[1] == b"one"
        ch.write(b"two")
        assert ch.read()[1] == b"two"
        with pytest.raises(ValueError):
            ch.write(b"x" * 65)
        ch.close()
        ch.unlink()


class TestInterpretedDag:
    def test_chain(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        ref = dag.execute(5)
        assert ray_tpu.get(ref) == 16

    def test_multi_output_and_input_attr(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(2)
        with InputNode() as inp:
            dag = MultiOutputNode([a.add.bind(inp[0]), b.add.bind(inp[1])])
        refs = dag.execute(10, 20)
        assert ray_tpu.get(refs) == [11, 22]


class TestCompiledDag:
    def test_linear_pipeline(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        compiled = dag.experimental_compile()
        try:
            for i in range(5):
                assert compiled.execute(i).get(timeout=10) == i + 11
        finally:
            compiled.teardown()

    def test_fan_out_fan_in(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(2)
        c = Adder.remote(0)
        with InputNode() as inp:
            x = a.add.bind(inp)
            y = b.add.bind(inp)
            dag = c.add2.bind(x, y)
        compiled = dag.experimental_compile()
        try:
            # (5+1) + (5+2) = 13
            assert compiled.execute(5).get(timeout=10) == 13
            assert compiled.execute(0).get(timeout=10) == 3
        finally:
            compiled.teardown()

    def test_multi_output(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(2)
        with InputNode() as inp:
            dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(1).get(timeout=10) == [2, 3]
        finally:
            compiled.teardown()

    def test_intra_actor_locality(self, ray_start):
        # Two stages on the same actor: values pass locally, no channel.
        a = Adder.remote(1)
        with InputNode() as inp:
            dag = a.add.bind(a.add.bind(inp))
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(0).get(timeout=10) == 2
            assert len(compiled._channels) == 2  # input edge + output edge
        finally:
            compiled.teardown()

    def test_pipelined_executions(self, ray_start):
        a = Adder.remote(1)
        with InputNode() as inp:
            dag = a.add.bind(inp)
        compiled = dag.experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(2)]
            assert [r.get(timeout=10) for r in refs] == [1, 2]
        finally:
            compiled.teardown()

    def test_error_propagation_keeps_pipeline_alive(self, ray_start):
        a = Adder.remote(1)
        b = Adder.remote(1)
        with InputNode() as inp:
            dag = b.add.bind(a.boom.bind(inp))
        compiled = dag.experimental_compile()
        try:
            with pytest.raises(Exception, match="kapow"):
                compiled.execute(1).get(timeout=10)
            # The loop survives an application error.
            with pytest.raises(Exception, match="kapow"):
                compiled.execute(2).get(timeout=10)
        finally:
            compiled.teardown()

    def test_numpy_payload(self, ray_start):
        a = Adder.remote(1.0)
        with InputNode() as inp:
            dag = a.add.bind(inp)
        compiled = dag.experimental_compile(buffer_size_bytes=1 << 22)
        try:
            arr = np.ones((256, 256), np.float32)
            out = compiled.execute(arr).get(timeout=15)
            np.testing.assert_allclose(out, arr + 1.0)
        finally:
            compiled.teardown()

    def test_actor_usable_after_teardown(self, ray_start):
        a = Adder.remote(1)
        with InputNode() as inp:
            dag = a.add.bind(inp)
        compiled = dag.experimental_compile()
        assert compiled.execute(1).get(timeout=10) == 2
        compiled.teardown()
        # Loop has exited; the actor serves normal calls again.
        assert ray_tpu.get(a.add.remote(41)) == 42
        with pytest.raises(RuntimeError):
            compiled.execute(1)

    def test_compile_validations(self, ray_start):
        a = Adder.remote(1)
        with InputNode() as inp:
            dag_no_input = a.add.bind(7)
        with pytest.raises(ValueError, match="depend on the InputNode"):
            dag_no_input.experimental_compile()


class TestRayCall:
    def test_ray_call_apply(self, ray_start):
        a = Adder.remote(5)
        ref = a.__ray_call__.remote(lambda self, k: self.inc * k, 4)
        assert ray_tpu.get(ref) == 20


class TestRevisitActorTopology:
    def test_actor_revisited_after_other_actor(self, ray_start):
        # a.add -> b.add -> a.add2: actor A's second step depends on B's
        # output.  With up-front (all-in-channels-first) reads A would
        # block on the B->A channel before running its first step — the
        # per-step read order makes this standard PP topology work.
        a = Adder.remote(1)
        b = Adder.remote(10)
        with InputNode() as inp:
            x = a.add.bind(inp)          # runs on A
            y = b.add.bind(x)            # runs on B
            dag = a.add2.bind(x, y)      # back on A, needs B's output
        compiled = dag.experimental_compile()
        try:
            # (5+1) + (5+1+10) = 22
            assert compiled.execute(5).get(timeout=10) == 22
            assert compiled.execute(0).get(timeout=10) == 12
        finally:
            compiled.teardown()


class TestTeardownSemantics:
    def test_get_after_teardown_returns_drained_result(self, ray_start):
        a = Adder.remote(1)
        with InputNode() as inp:
            dag = a.add.bind(inp)
        compiled = dag.experimental_compile()
        ref = compiled.execute(4)
        compiled.teardown()
        # Result was drained into the cache during teardown.
        assert ref.get(timeout=5) == 5

    def test_get_timeout_does_not_desync_outputs(self, ray_start):
        import time as _t

        @ray_tpu.remote
        class Slow:
            def fast(self, x):
                return x

            def slow(self, x):
                _t.sleep(1.0)
                return x * 10

        f, s = Slow.remote(), Slow.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([f.fast.bind(inp), s.slow.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            ref = compiled.execute(3)
            with pytest.raises(TimeoutError):
                ref._dag._fetch(0, timeout=0.1)
            # Retry succeeds with outputs correctly paired.
            assert ref.get(timeout=10) == [3, 30]
        finally:
            compiled.teardown()


class TestCollectiveNodes:
    """Allreduce across actor outputs (reference: dag/collective_node.py:23
    — NCCL allreduce in compiled graphs; here peer-to-peer shm channels
    with local reduction)."""

    def _workers(self, n=3):
        import numpy as np

        @ray_tpu.remote
        class Shard:
            def __init__(self, scale):
                self.scale = scale

            def grad(self, x):
                return np.asarray(x, np.float32) * self.scale

            def norm(self, g):
                return float(np.sum(g))

        return [Shard.remote(i + 1) for i in range(n)]

    def test_interpreted_allreduce(self, ray_start):
        import numpy as np
        from ray_tpu.dag import InputNode, MultiOutputNode, allreduce_bind
        w = self._workers()
        with InputNode() as inp:
            grads = [wi.grad.bind(inp) for wi in w]
            red = allreduce_bind(grads, op="sum")
            node = MultiOutputNode(
                [wi.norm.bind(r) for wi, r in zip(w, red)])
        vals = ray_tpu.get(node.execute(np.ones(4)))
        assert vals == [24.0, 24.0, 24.0]

    def test_compiled_allreduce_many_iterations(self, ray_start):
        import numpy as np
        from ray_tpu.dag import InputNode, MultiOutputNode, allreduce_bind
        w = self._workers()
        with InputNode() as inp:
            grads = [wi.grad.bind(inp) for wi in w]
            red = allreduce_bind(grads, op="sum")
            node = MultiOutputNode(
                [wi.norm.bind(r) for wi, r in zip(w, red)])
        dag = node.experimental_compile()
        try:
            for trial in range(5):
                got = dag.execute(np.full(4, trial + 1.0)).get(timeout=30)
                assert got == [24.0 * (trial + 1)] * 3
        finally:
            dag.teardown()

    def test_compiled_mean_over_pytree(self, ray_start):
        import numpy as np
        from ray_tpu.dag import InputNode, MultiOutputNode, allreduce_bind

        @ray_tpu.remote
        class P:
            def __init__(self, k):
                self.k = k

            def make(self, x):
                return {"a": np.full(2, self.k, np.float32),
                        "b": float(self.k * 10)}

            def read(self, t):
                return (t["a"].tolist(), t["b"])

        w = [P.remote(1), P.remote(3)]
        with InputNode() as inp:
            parts = [wi.make.bind(inp) for wi in w]
            red = allreduce_bind(parts, op="mean")
            node = MultiOutputNode(
                [wi.read.bind(r) for wi, r in zip(w, red)])
        dag = node.experimental_compile()
        try:
            got = dag.execute(0).get(timeout=30)
            assert got == [([2.0, 2.0], 20.0), ([2.0, 2.0], 20.0)]
        finally:
            dag.teardown()

    def test_validation(self, ray_start):
        import numpy as np
        from ray_tpu.dag import InputNode, allreduce_bind
        w = self._workers(2)
        with InputNode() as inp:
            g0 = w[0].grad.bind(inp)
            g1 = w[1].grad.bind(inp)
            same = w[0].grad.bind(inp)
        # distinct actors required
        with pytest.raises(ValueError, match="distinct actors"):
            allreduce_bind([g0, same])
        with pytest.raises(ValueError, match="participants"):
            allreduce_bind([g0])
        with pytest.raises(ValueError, match="unsupported"):
            allreduce_bind([g0, g1], op="xor")
        # all outputs must be in the compiled DAG
        red = allreduce_bind([g0, g1], op="sum")
        only = w[0].norm.bind(red[0])
        with pytest.raises(ValueError, match="outputs of a collective"):
            only.experimental_compile()

    def test_error_propagates_through_collective(self, ray_start):
        import numpy as np
        from ray_tpu.dag import InputNode, MultiOutputNode, allreduce_bind

        @ray_tpu.remote
        class Flaky:
            def __init__(self, fail):
                self.fail = fail

            def grad(self, x):
                if self.fail and x > 1:
                    raise RuntimeError("shard exploded")
                return np.ones(2, np.float32)

            def norm(self, g):
                return float(np.sum(g))

        w = [Flaky.remote(False), Flaky.remote(True)]
        with InputNode() as inp:
            grads = [wi.grad.bind(inp) for wi in w]
            red = allreduce_bind(grads, op="sum")
            node = MultiOutputNode(
                [wi.norm.bind(r) for wi, r in zip(w, red)])
        dag = node.experimental_compile()
        try:
            assert dag.execute(0).get(timeout=30) == [4.0, 4.0]
            with pytest.raises(ray_tpu.TaskError, match="shard exploded"):
                dag.execute(5).get(timeout=30)
            # Pipeline stays usable after the error iteration.
            assert dag.execute(1).get(timeout=30) == [4.0, 4.0]
        finally:
            dag.teardown()
