"""Disaggregated serving tests: chunked prefill exactness, KV-pressure
preemption/re-admission, prefill->decode KV handoff (direct + through the
shm object store), SLO-aware admission shedding, and the serve_load
saturation smoke (the tier-1 half of the serve_load bench contract).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import InferenceEngine, LLMServer, SamplingParams
from ray_tpu.models import LlamaConfig
from ray_tpu.models.llama import forward, init_params

CFG = LlamaConfig(vocab_size=128, hidden=32, layers=2, heads=4, kv_heads=2,
                  head_dim=8, mlp_dim=64, max_seq_len=128,
                  dtype=jnp.float32, attention_impl="reference", remat=False)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


_GOLD: dict = {}


def naive_greedy(params, prompt, max_new):
    """Gold stream via full re-forward per token; memoized — the
    KV-pressure tests replay the same prompts across three drive
    modes and 80 forwards per replay would dominate tier-1 time."""
    key = (tuple(prompt), max_new)
    if key in _GOLD:
        return list(_GOLD[key])
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = forward(params, jnp.asarray([toks]), CFG)
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    _GOLD[key] = list(out)
    return out


class TestChunkedPrefill:
    def test_matches_monolithic_greedy(self, params):
        """Chunked prefill (8-token chunks across decode steps) produces
        exactly the monolithic-prefill greedy stream — including a
        prompt LONGER than every bucket, which only the chunked program
        can cover."""
        rng = np.random.default_rng(7)
        long_prompt = rng.integers(1, CFG.vocab_size, 37).tolist()
        short = [3, 17, 92, 5]
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,),
                              prefill_chunk=8)
        outs = eng.generate([long_prompt, short],
                            SamplingParams(max_tokens=6))
        assert outs[0] == naive_greedy(params, long_prompt, 6)
        assert outs[1] == naive_greedy(params, short, 6)

    def test_interleaves_with_decode(self, params):
        """While a long prompt chunk-prefills, an already-running
        request keeps decoding: its tokens advance between prefill
        chunks instead of stalling until the prompt is done."""
        rng = np.random.default_rng(11)
        long_prompt = rng.integers(1, CFG.vocab_size, 48).tolist()
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,),
                              prefill_chunk=8)
        sp = SamplingParams(max_tokens=30)
        short_id = eng.add_request([5, 6, 7], sp)
        eng.step()          # admit + first decode of the short request
        eng.add_request(long_prompt, SamplingParams(max_tokens=4))
        short_req = eng.running[short_id]
        eng.step()            # admits the long prompt into chunked state
        assert eng._prefilling
        progress = [len(short_req.output_tokens)]
        while eng._prefilling:
            eng.step()
            progress.append(len(short_req.output_tokens))
        # The short request decoded DURING the chunked prefill.
        assert progress[-1] > progress[0]
        while eng.has_work():
            eng.step()
        assert short_req.output_tokens == naive_greedy(
            params, [5, 6, 7], 30)


class TestKVPressure:
    """PagePool exhaustion mid-decode: lazy page allocation preempts the
    youngest request, re-queues it at the FRONT, and recompute
    re-admission reproduces the exact greedy stream."""

    def _run(self, params, drive, num_pages=14, max_tokens=20):
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, CFG.vocab_size, 6).tolist()
                   for _ in range(4)]
        want = [naive_greedy(params, p, max_tokens) for p in prompts]
        eng = InferenceEngine(params, CFG, max_slots=4, page_size=4,
                              num_pages=num_pages, prefill_buckets=(16,))
        preempts = []
        orig = type(eng)._preempt

        def counting(self, slot):
            preempts.append(self.slot_req[slot].request_id)
            return orig(self, slot)
        eng._preempt = counting.__get__(eng)
        free0 = eng.pool.num_free
        ids = [eng.add_request(p, SamplingParams(max_tokens=max_tokens))
               for p in prompts]
        done = {}
        if drive == "pipelined":
            done = {r.request_id: r.output_tokens
                    for r in eng.run_pipelined(4, max_chunks=8000)}
        else:
            guard = 0
            while eng.has_work():
                rs = eng.step() if drive == "step" else eng.step_chunk(4)
                for r in rs:
                    done[r.request_id] = r.output_tokens
                guard += 1
                assert guard < 8000
        got = [done[i] for i in ids]
        assert got == want
        assert eng.pool.num_free == free0   # no page leaks
        return preempts

    def test_preemption_step_path(self, params):
        preempts = self._run(params, "step")
        assert preempts, "pool was sized to force preemption"

    def test_preemption_chunk_path(self, params):
        self._run(params, "chunk")

    def test_preemption_pipelined_path(self, params):
        self._run(params, "pipelined")

    def test_readmission_fairness(self, params):
        """Preempted requests re-queue at the FRONT: re-admission keeps
        arrival order ahead of never-admitted requests."""
        rng = np.random.default_rng(5)
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=4,
                              num_pages=10, prefill_buckets=(16,))
        sp = SamplingParams(max_tokens=16)
        prompts = [rng.integers(1, CFG.vocab_size, 5).tolist()
                   for _ in range(4)]
        ids = [eng.add_request(p, sp) for p in prompts]
        done = {}
        order = []
        guard = 0
        while eng.has_work():
            for r in eng.step():
                done[r.request_id] = r.output_tokens
                order.append(r.request_id)
            guard += 1
            assert guard < 8000
        # All exact despite churn, and the first arrival finishes before
        # the last (FIFO preserved through preempt/re-admit cycles).
        for rid, p in zip(ids, prompts):
            assert done[rid] == naive_greedy(params, p, 16)
        assert set(order) == set(ids)
        assert order.index(ids[0]) < order.index(ids[3])


class TestKVHandoff:
    def test_import_prefill_continues_exact(self, params):
        """A decode engine importing a PrefillWorker's handoff produces
        the same greedy stream as local end-to-end generation."""
        from ray_tpu.llm.disagg import PrefillWorker

        prompt = [3, 17, 92, 5, 41]
        pw = PrefillWorker(params, CFG, prefill_buckets=(16,), page_size=8)
        h = pw.prefill(prompt, SamplingParams(max_tokens=8))
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,))
        rid = eng.import_prefill(h)
        assert rid is not None
        done = {}
        while eng.has_work():
            for r in eng.step():
                done[r.request_id] = r.output_tokens
        assert done[rid] == naive_greedy(params, prompt, 8)

    def test_handoff_through_object_store(self, params):
        """Same-host handoff through the shm object store: export seals
        a page blob, import maps it back (zero-copy views), and the
        decode stream is exact; the staged blob is deleted after
        import."""
        from ray_tpu._private.object_store import SharedMemoryStore
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.llm.disagg import (PrefillWorker, export_handoff,
                                        import_handoff)

        prompt = [7, 9, 23, 6]
        pw = PrefillWorker(params, CFG, prefill_buckets=(16,), page_size=8)
        h = pw.prefill(prompt, SamplingParams(max_tokens=6))
        store = SharedMemoryStore(capacity_bytes=32 << 20)
        try:
            oid = ObjectID.from_random()
            desc = export_handoff(store, oid, h)
            assert desc is not None
            h2, keepalive = import_handoff(desc)
            assert h2.prompt_tokens == h.prompt_tokens
            assert h2.first_token == h.first_token
            np.testing.assert_array_equal(np.asarray(h2.ks),
                                          np.asarray(h.ks))
            eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                                  num_pages=64, prefill_buckets=(16,))
            rid = eng.import_prefill(h2)
            del keepalive
            store.delete(oid)
            assert store.stats()["num_objects"] == 0
            done = {}
            while eng.has_work():
                for r in eng.step():
                    done[r.request_id] = r.output_tokens
            assert done[rid] == naive_greedy(params, prompt, 6)
        finally:
            store.shutdown()

    def test_decode_full_returns_none(self, params):
        """import_prefill under decode-side pressure returns None
        (caller backpressure) instead of silently dropping."""
        from ray_tpu.llm.disagg import PrefillWorker

        pw = PrefillWorker(params, CFG, prefill_buckets=(16,), page_size=8)
        eng = InferenceEngine(params, CFG, max_slots=1, page_size=8,
                              num_pages=64, prefill_buckets=(16,))
        h1 = pw.prefill([1, 2, 3], SamplingParams(max_tokens=8))
        h2 = pw.prefill([4, 5, 6], SamplingParams(max_tokens=8))
        assert eng.import_prefill(h1) is not None
        assert eng.import_prefill(h2) is None  # no free slot
        while eng.has_work():
            eng.step()
        assert eng.import_prefill(h2) is not None


ENGINE_OPTS = {"max_slots": 2, "page_size": 8, "num_pages": 64,
               "prefill_buckets": (16,)}


class TestDisaggServer:
    def test_all_modes_exact(self, params):
        from ray_tpu.llm.disagg import DisaggServer

        prompt = [3, 17, 92, 5, 41]
        want = naive_greedy(params, prompt, 6)
        for mode in ("inline", "chunked", "disagg"):
            srv = DisaggServer(lambda: (params, CFG), mode=mode,
                               engine_options=dict(ENGINE_OPTS),
                               record_token_times=True)
            try:
                out = srv({"prompt_tokens": prompt, "max_tokens": 6,
                           "timeout_s": 120})
                assert out["output_tokens"] == want, mode
                assert out["finish_reason"] == "length"
                assert out["ttft_s"] is not None and out["ttft_s"] >= 0
            finally:
                srv.close()

    def test_admission_sheds_not_queues(self, params):
        """Past the class queue bound, submit raises a retriable
        OverloadError immediately — overload never becomes a silent
        timeout."""
        from ray_tpu.llm.disagg import (AdmissionConfig, DisaggServer,
                                        OverloadError, RequestClass)

        adm = AdmissionConfig(classes={"default": RequestClass(
            max_queue_depth=2, queue_deadline_s=30.0)})
        srv = DisaggServer(lambda: (params, CFG), mode="inline",
                           engine_options=dict(ENGINE_OPTS), admission=adm)
        try:
            shed = 0
            ids = []
            for _ in range(40):
                try:
                    ids.append(srv.submit({"prompt_tokens": [5, 6, 7],
                                           "max_tokens": 12}))
                except OverloadError as e:
                    assert e.retriable
                    shed += 1
            assert shed > 0
            # Admitted requests still complete.
            res = srv.result(ids[0], timeout_s=120)
            assert res["finish_reason"] == "length"
        finally:
            srv.close()

    def test_class_token_budget(self, params):
        from ray_tpu.llm.disagg import (AdmissionConfig, DisaggServer,
                                        OverloadError, RequestClass)

        adm = AdmissionConfig(classes={"default": RequestClass(
            token_budget=40, max_queue_depth=64)})
        srv = DisaggServer(lambda: (params, CFG), mode="inline",
                           engine_options=dict(ENGINE_OPTS), admission=adm)
        try:
            srv.submit({"prompt_tokens": [1, 2, 3], "max_tokens": 30})
            with pytest.raises(OverloadError, match="class_budget"):
                srv.submit({"prompt_tokens": [1, 2, 3], "max_tokens": 30})
        finally:
            srv.close()

    def test_serve_load_saturation_smoke(self, params):
        """Tier-1 serve_load contract: under forced saturation (open-
        loop arrivals far past capacity, tiny queue bounds) the router
        SHEDS instead of queueing unboundedly, and p99 TTFT of ADMITTED
        requests stays bounded."""
        from ray_tpu.llm.disagg import (AdmissionConfig, DisaggServer,
                                        RequestClass, ServeLoadSpec,
                                        run_open_loop)

        adm = AdmissionConfig(classes={
            "interactive": RequestClass("interactive", token_budget=200,
                                        max_queue_depth=4,
                                        queue_deadline_s=1.5),
            "batch": RequestClass("batch", token_budget=120,
                                  max_queue_depth=2,
                                  queue_deadline_s=1.5),
            "default": RequestClass()})
        srv = DisaggServer(lambda: (params, CFG), mode="chunked",
                           engine_options=dict(ENGINE_OPTS), admission=adm,
                           record_token_times=True)
        try:
            spec = ServeLoadSpec(rps=60, duration_s=2.0,
                                 long_fraction=0.3, short_prompt=6,
                                 short_max_tokens=12, long_prompt=14,
                                 long_max_tokens=6, drain_timeout_s=120)
            r = run_open_loop(srv, spec, vocab_size=CFG.vocab_size)
        finally:
            srv.close()
        assert r["offered"] > 20
        assert r["shed_submit"] + r["shed_deadline"] > 0, \
            "saturation must activate shedding"
        assert r["completed"] > 0
        assert r["unfinished"] == 0 and r["errors"] == 0
        # Bounded TTFT for admitted work: the queue deadline caps time-
        # to-dispatch, so admitted p99 TTFT can't grow with offered load.
        assert r["ttft_p99_ms"] is not None and r["ttft_p99_ms"] < 5000.0


class TestLLMServerLifecycle:
    def test_close_joins_drive_thread(self, params):
        srv = LLMServer(lambda: (params, CFG),
                        engine_options=dict(ENGINE_OPTS))
        assert srv._thread.is_alive()
        srv.close()
        assert not srv._thread.is_alive()

    def test_submit_kicks_drive_event(self, params):
        """No sleep-poll: a submitted request completes promptly because
        submit sets the work event (the old 5 ms poll is gone)."""
        srv = LLMServer(lambda: (params, CFG),
                        engine_options=dict(ENGINE_OPTS))
        try:
            out = srv({"prompt_tokens": [5, 6, 7], "max_tokens": 4,
                       "timeout_s": 120})
            assert out["finish_reason"] == "length"
        finally:
            srv.close()

    def test_abandoned_request_swept(self, params, monkeypatch):
        """A caller that vanishes after submit leaves no engine slot,
        pages, or _events/_results entries behind once its deadline +
        grace passes."""
        from ray_tpu.llm import serving as serving_mod

        monkeypatch.setattr(serving_mod, "_ABANDON_GRACE_S", 0.2)
        srv = LLMServer(lambda: (params, CFG),
                        engine_options=dict(ENGINE_OPTS))
        try:
            free0 = srv.engine.pool.num_free
            # Submit and never wait: max_tokens large enough that it is
            # still running when the deadline (0 + grace) passes.
            rid, _ev = srv._submit([5, 6, 7],
                                   SamplingParams(max_tokens=4),
                                   timeout_s=0.0)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with srv._lock:
                    clean = rid not in srv._events \
                        and rid not in srv._results \
                        and rid not in srv._deadlines
                if clean and srv.engine.pool.num_free == free0 \
                        and rid not in srv.engine.running:
                    break
                time.sleep(0.05)
            with srv._lock:
                assert rid not in srv._events
                assert rid not in srv._results
                assert rid not in srv._deadlines
            assert rid not in srv.engine.running
            assert srv.engine.pool.num_free == free0
        finally:
            srv.close()
