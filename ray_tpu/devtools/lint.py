"""AST rule engine behind ``ray-tpu lint``.

The engine is deliberately small: a rule is an object with an ``id``, a
``scope`` and a ``check(ctx)`` generator over one parsed module.  Rules
self-register at import (``rules_user`` / ``rules_internal`` at the
bottom of this file), findings are suppressible per line with
``# ray-tpu: noqa[RT201]`` (or a bare ``# ray-tpu: noqa`` for all
rules), and output is text or JSON.

Scopes:

* ``user`` rules understand ``ray_tpu`` *usage* (anti-patterns from the
  docs: nested blocking ``get``, ``get``-in-a-loop, bad captures) and
  run over every linted file.
* ``internal`` rules are invariants of the framework's own source
  (locks, swallowed exceptions, monotonic clocks, telemetry catalog,
  protocol completeness) and only run on files inside the ``ray_tpu``
  package tree (auto-detected from the path; override with
  ``internal=``).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

_NOQA_RE = re.compile(
    r"#\s*ray-tpu:\s*noqa(?:\[(?P<rules>[A-Za-z0-9_,\s]+)\])?")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    #: Additional lines where a ``# ray-tpu: noqa`` suppresses this
    #: finding (e.g. the ``with`` statement owning a blocking call).
    anchor_lines: Tuple[int, ...] = ()

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"


@dataclass
class LintResult:
    findings: List[Finding]
    files_checked: int
    #: rule id -> number of findings silenced by ``# ray-tpu: noqa``
    #: comments.  Reported (not hidden) so the suppression debt stays
    #: visible in every lint run.
    suppressed: Dict[str, int] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.suppressed is None:
            self.suppressed = {}

    @property
    def ok(self) -> bool:
        return not self.findings


class ModuleContext:
    """One parsed module handed to every rule."""

    def __init__(self, tree: ast.Module, source: str, path: str,
                 internal: bool):
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self.path = path
        # Normalized forward-slash path for module-identity checks
        # (e.g. RT202's control-plane set, RT205's anchor file).
        self.module_key = path.replace(os.sep, "/")
        self.internal = internal
        self._by_type: Optional[Dict[type, List[ast.AST]]] = None

    def nodes(self, *types: type) -> List[ast.AST]:
        """All nodes of the given AST types, from ONE shared full-tree
        walk (rules iterating ast.walk() independently dominated lint
        wall time; the index makes each rule a dict lookup)."""
        if self._by_type is None:
            by_type: Dict[type, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                by_type.setdefault(type(node), []).append(node)
            self._by_type = by_type
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        return out

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                anchors: Sequence[ast.AST] = ()) -> Finding:
        return Finding(rule.id, self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, message,
                       tuple(getattr(a, "lineno", 1) for a in anchors))


class Rule:
    """Base class; subclasses set the metadata and implement check()."""

    id: str = "RT000"
    summary: str = ""
    rationale: str = ""
    scope: str = "user"  # "user" | "internal"
    #: True for rules that run over the per-function CFG
    #: (devtools/dataflow.py) rather than single AST nodes.
    dataflow: bool = False
    #: Optional snippets for ``ray-tpu lint --explain RULE``.
    example_bad: str = ""
    example_good: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError


_RULES: List[Rule] = []


def register(cls):
    _RULES.append(cls())
    return cls


def iter_rules() -> List[Rule]:
    return list(_RULES)


# -- shared AST helpers (used by the rule modules) --------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node`` without descending into nested function/class
    bodies (code that does not execute in the enclosing scope)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


# -- noqa suppression -------------------------------------------------------


def _noqa_map(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule ids (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        if "ray-tpu" not in line:
            continue
        m = _NOQA_RE.search(line)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = None
        else:
            ids = {r.strip().upper() for r in rules.split(",") if r.strip()}
            prev = out.get(i, set())
            out[i] = None if prev is None else (prev or set()) | ids
    return out


def _suppressed(f: Finding, noqa: Dict[int, Optional[Set[str]]]) -> bool:
    for line in (f.line,) + f.anchor_lines:
        if line in noqa:
            allowed = noqa[line]
            if allowed is None or f.rule in allowed:
                return True
    return False


# -- running ----------------------------------------------------------------


def lint_source(source: str, path: str = "<snippet>",
                internal: bool = False,
                rules: Optional[Sequence[Rule]] = None,
                suppressed_counts: Optional[Dict[str, int]] = None,
                ) -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("RT001", path, e.lineno or 1, (e.offset or 0) + 1,
                        f"syntax error: {e.msg}")]
    ctx = ModuleContext(tree, source, path, internal)
    noqa = _noqa_map(source)
    out: List[Finding] = []
    for rule in (rules if rules is not None else _RULES):
        if rule.scope == "internal" and not internal:
            continue
        for f in rule.check(ctx):
            if not _suppressed(f, noqa):
                out.append(f)
            elif suppressed_counts is not None:
                suppressed_counts[f.rule] = \
                    suppressed_counts.get(f.rule, 0) + 1
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def _is_internal_path(path: str) -> bool:
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    return "ray_tpu" in parts


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            for fname in sorted(files):
                if fname.endswith(".py"):
                    yield os.path.join(root, fname)


def changed_python_files(base: str = "HEAD",
                         repo_root: Optional[str] = None) -> List[str]:
    """Python files modified per ``git diff <base>`` plus untracked ones
    — the ``ray-tpu lint --changed`` pre-commit set.  Raises
    RuntimeError when git fails (not a repo, unknown ref): a broken
    diff must be loud, never an empty green run."""
    import subprocess
    root = os.path.abspath(repo_root or os.getcwd())
    def _git(*args: str) -> List[str]:
        proc = subprocess.run(["git", *args], cwd=root,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                proc.stderr.strip() or f"git {' '.join(args)} failed")
        return proc.stdout.splitlines()
    top = _git("rev-parse", "--show-toplevel")[0]
    names = _git("diff", "--name-only", "--diff-filter=d", base, "--")
    names += _git("ls-files", "--others", "--exclude-standard")
    out: List[str] = []
    for name in names:
        if not name.endswith(".py"):
            continue
        path = os.path.join(top, name)
        if os.path.exists(path) and path not in out:
            out.append(path)
    return sorted(out)


def lint_paths(paths: Sequence[str],
               internal: Optional[bool] = None,
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    """Lint files/directories.  ``internal=None`` auto-detects per file:
    internal rules apply to files living under a ``ray_tpu`` package
    directory."""
    findings: List[Finding] = []
    suppressed: Dict[str, int] = {}
    n = 0
    # A missing input is a loud error, never a green no-op: a typo'd CI
    # path must not turn the lint gate into `0 findings in 0 files`.
    for p in paths:
        if not os.path.exists(p):
            findings.append(Finding("RT002", p, 1, 1,
                                    "no such file or directory"))
    for fpath in iter_python_files(paths):
        n += 1
        try:
            with open(fpath, encoding="utf-8", errors="replace") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("RT002", fpath, 1, 1,
                                    f"unreadable file: {e}"))
            continue
        is_internal = _is_internal_path(fpath) if internal is None \
            else internal
        findings.extend(lint_source(source, fpath, internal=is_internal,
                                    rules=rules,
                                    suppressed_counts=suppressed))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings, n, suppressed)


# -- output -----------------------------------------------------------------


def format_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    tail = f"{len(result.findings)} finding(s) in " \
           f"{result.files_checked} file(s)"
    if result.suppressed:
        per = ", ".join(f"{rid}×{n}" for rid, n in
                        sorted(result.suppressed.items()))
        tail += f"; {sum(result.suppressed.values())} suppressed ({per})"
    lines.append(tail)
    return "\n".join(lines)


def format_json(result: LintResult) -> str:
    summaries = {r.id: r.summary for r in _RULES}
    return json.dumps({
        "version": 1,
        "files_checked": result.files_checked,
        "suppressed": dict(sorted(result.suppressed.items())),
        "findings": [dict(f.to_dict(),
                          explain=summaries.get(f.rule, ""))
                     for f in result.findings],
    }, indent=1)


def _gh_escape(text: str) -> str:
    """GitHub workflow-command property/data escaping."""
    return text.replace("%", "%25").replace("\r", "%0D").replace("\n",
                                                                 "%0A")


def format_github(result: LintResult) -> str:
    """GitHub annotations (`::error file=...`) — one line per finding,
    so a CI step surfaces findings inline on the PR diff."""
    lines = []
    for f in result.findings:
        lines.append(
            f"::error file={_gh_escape(f.path)},line={f.line},"
            f"col={f.col},title={f.rule}::"
            f"{_gh_escape(f.rule + ' ' + f.message)}")
    return "\n".join(lines)


def rule_catalog_text() -> str:
    lines = []
    for rule in _RULES:
        tags = rule.scope + (", dataflow" if rule.dataflow else "")
        lines.append(f"{rule.id} [{tags}] {rule.summary}")
        if rule.rationale:
            lines.append(f"    {rule.rationale}")
    return "\n".join(lines)


def explain_text(rule_id: str) -> Optional[str]:
    """Human explanation of one rule for ``ray-tpu lint --explain``:
    summary, rationale, bad/good example (when recorded) and the
    suppression syntax.  None for an unknown rule id."""
    rid = rule_id.strip().upper()
    rule = next((r for r in _RULES if r.id == rid), None)
    if rule is None:
        return None
    tags = rule.scope + (", dataflow-backed" if rule.dataflow else "")
    lines = [f"{rule.id} [{tags}] — {rule.summary}", ""]
    if rule.rationale:
        lines += [rule.rationale, ""]
    if rule.example_bad:
        lines.append("Bad:")
        lines += ["    " + ln for ln in rule.example_bad.rstrip().
                  splitlines()]
        lines.append("")
    if rule.example_good:
        lines.append("Good:")
        lines += ["    " + ln for ln in rule.example_good.rstrip().
                  splitlines()]
        lines.append("")
    lines.append(f"Suppress a deliberate violation on its line with "
                 f"`# ray-tpu: noqa[{rule.id}]` "
                 f"(bare `# ray-tpu: noqa` suppresses every rule).")
    return "\n".join(lines)


# Rule modules self-register on import; they import helpers from this
# module, so this must stay at the bottom.
from . import (rules_concurrency, rules_dataflow, rules_internal,  # noqa: E402,F401
               rules_jax, rules_user)
