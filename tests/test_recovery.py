"""Ownership GC + lineage reconstruction tests (reference analogs:
python/ray/tests/test_reference_counting.py, test_object_reconstruction*.py
over ReferenceCounter reference_counter.h:44 and ObjectRecoveryManager
object_recovery_manager.h:41)."""

from __future__ import annotations

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture()
def rt():
    runtime = ray_tpu.init(num_cpus=4)
    yield runtime
    ray_tpu.shutdown()


class TestOwnershipGC:
    def test_put_refs_freed_on_drop(self, rt):
        stats0 = rt.node.store.stats()
        refs = [ray_tpu.put(np.zeros(200_000)) for _ in range(5)]
        assert rt.node.store.stats()["num_objects"] >= \
            stats0["num_objects"] + 5
        del refs
        gc.collect()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if rt.node.store.stats()["num_objects"] <= stats0["num_objects"]:
                break
            time.sleep(0.05)
        assert rt.node.store.stats()["num_objects"] <= stats0["num_objects"]

    def test_directory_bounded_in_task_loop(self, rt):
        @ray_tpu.remote
        def noop(i):
            return i

        for i in range(300):
            assert ray_tpu.get(noop.remote(i)) == i
        gc.collect()
        time.sleep(0.3)
        # Without GC the directory would hold >=300 entries.
        assert len(rt.directory) < 100

    def test_in_flight_dependency_not_collected(self, rt):
        @ray_tpu.remote
        def make():
            return np.ones(200_000)

        @ray_tpu.remote
        def total(a, delay):
            time.sleep(delay)
            return float(a.sum())

        # The intermediate ref is dropped immediately after being passed.
        out_ref = total.remote(make.remote(), 0.5)
        gc.collect()
        assert ray_tpu.get(out_ref, timeout=30) == 200_000.0

    def test_escaped_ref_not_collected(self, rt):
        import pickle
        inner = ray_tpu.put(np.arange(50_000))
        # Pickling OUTSIDE any runtime serialization context (user dumps
        # to disk/network): copies can live anywhere — escaped forever.
        blob = pickle.dumps([inner])
        inner_id = inner.id()
        del inner
        gc.collect()
        time.sleep(0.2)
        got = pickle.loads(blob)
        assert ray_tpu.get(got[0])[-1] == 49_999
        assert inner_id in rt._escaped

    def test_put_containment_holds_then_releases(self, rt):
        """A ref inside a put() value is retained by the OUTER object —
        not pinned forever: dropping the inner handle keeps it alive
        while the holder lives; freeing the holder frees it (reference:
        reference_counter.h:44 nested-ref containment)."""
        inner = ray_tpu.put(np.arange(50_000))
        holder = ray_tpu.put([inner])
        inner_id = inner.id()
        del inner
        gc.collect()
        time.sleep(0.2)
        got = ray_tpu.get(holder)
        assert ray_tpu.get(got[0])[-1] == 49_999
        assert inner_id not in rt._escaped
        del got
        del holder
        gc.collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with rt._dir_lock:
                gone = inner_id not in rt.directory
            if gone:
                break
            time.sleep(0.05)
        assert gone, "contained object not freed with its holder"
        assert not rt._contained

    def test_nested_ref_through_two_actors_releases_slot(self, rt):
        """The round-5 target scenario: a ref buried in a dataclass
        passes through TWO actors (arg containment in, RESULT containment
        out at each hop); when every handle drops, the arena slot is
        reclaimed (reference: reference_counter.h:44)."""
        from dataclasses import dataclass

        @dataclass
        class Box:
            ref: object
            tag: str = ""

        @ray_tpu.remote
        class Courier:
            def forward(self, box):
                # Returns a NEW dataclass still containing the ref: the
                # result object becomes the container.
                return Box(box.ref, box.tag + "x")

        a, b = Courier.remote(), Courier.remote()
        payload = ray_tpu.put(np.ones(300_000))   # arena-resident
        oid = payload.id()
        stats_before = rt.node.store.stats()["num_objects"]
        box1 = ray_tpu.get(a.forward.remote(Box(payload)), timeout=60)
        box2 = ray_tpu.get(b.forward.remote(box1), timeout=60)
        assert box2.tag == "xx"
        assert float(ray_tpu.get(box2.ref).sum()) == 300_000.0
        assert oid not in rt._escaped
        del payload, box1, box2
        gc.collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with rt._dir_lock:
                gone = oid not in rt.directory
            if gone:
                break
            time.sleep(0.05)
        assert gone, "nested ref still pinned after all handles dropped"
        # Arena slot actually reclaimed.
        assert rt.node.store.stats()["num_objects"] <= stats_before

    def test_nested_ref_borrow_released_after_two_hops(self, rt):
        """A ref pickled INSIDE task args is a tracked borrow, not an
        escaped-forever pin: after it travels through two worker hops and
        every handle drops, the object is freed and its arena slot is
        reusable (reference: reference_counter.h:44 borrow chain
        draining)."""
        @ray_tpu.remote
        def hop2(wrapped):
            return float(ray_tpu.get(wrapped[0]).sum())

        @ray_tpu.remote
        def hop1(wrapped):
            return ray_tpu.get(hop2.remote([wrapped[0]]), timeout=60)

        ref = ray_tpu.put(np.ones(300_000))  # arena-resident
        oid = ref.id()
        assert ray_tpu.get(hop1.remote([ref]), timeout=60) == 300_000.0
        assert oid not in rt._escaped
        stats_before = rt.node.store.stats()["num_objects"]
        del ref
        gc.collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with rt._dir_lock:
                gone = oid not in rt.directory
            if gone:
                break
            time.sleep(0.05)
        assert gone, "borrowed object not freed after handles dropped"
        assert rt.node.store.stats()["num_objects"] <= stats_before

    def test_borrow_retained_by_actor_escalates_to_escape(self, rt):
        """The bounded fallback: a worker that KEEPS a borrowed ref past
        its task (actor state) reports it, and the owner pins the object
        so later reads still work."""
        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.held = None

            def keep(self, wrapped):
                self.held = wrapped[0]
                return "kept"

            def read(self):
                return float(ray_tpu.get(self.held).sum())

        k = Keeper.remote()
        ref = ray_tpu.put(np.ones(300_000))
        oid = ref.id()
        assert ray_tpu.get(k.keep.remote([ref]), timeout=60) == "kept"
        del ref
        gc.collect()
        time.sleep(0.5)
        # Escalated: not collected, still readable through the actor.
        assert oid in rt._escaped
        assert ray_tpu.get(k.read.remote(), timeout=60) == 300_000.0


class TestLineageReconstruction:
    def test_reconstruct_lost_object_on_get(self, rt):
        @ray_tpu.remote
        def produce():
            return np.arange(300_000, dtype=np.float64)

        ref = produce.remote()
        arr = ray_tpu.get(ref)
        assert arr[-1] == 299_999
        # While a zero-copy view is alive, an explicit free must DEFER
        # (freeing the arena slot would corrupt `arr`).
        rt.free([ref.id()])
        assert ray_tpu.get(ref, timeout=10)[-1] == 299_999
        # Once the views die, the deferred free lands and the directory
        # entry disappears.
        del arr
        gc.collect()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if ref.id() not in rt.directory:
                break
            time.sleep(0.05)

    def test_reconstruct_store_deleted_object(self, rt):
        @ray_tpu.remote
        def produce():
            return np.arange(250_000, dtype=np.float64)

        ref = produce.remote()
        assert ray_tpu.get(ref)[-1] == 249_999
        # Delete the bytes but keep the (now stale) directory entry — the
        # realistic loss mode (node restart, spill dir wiped).
        rt.node.store.delete(ref.id())
        arr2 = ray_tpu.get(ref, timeout=60)
        assert arr2[-1] == 249_999  # rebuilt by re-executing produce()

    def test_reconstruct_dependency_chain(self, rt):
        @ray_tpu.remote
        def base():
            return np.full(200_000, 3.0)

        @ray_tpu.remote
        def double(a):
            return a * 2

        x = base.remote()
        y = double.remote(x)
        assert ray_tpu.get(y)[0] == 6.0
        # Lose both the intermediate and the final object.
        rt.node.store.delete(x.id())
        rt.node.store.delete(y.id())
        out = ray_tpu.get(y, timeout=60)
        assert out[0] == 6.0

    def test_reconstruct_after_dep_was_gcd(self, rt):
        """A lost object whose input was already GC'd: recovery must
        recursively rebuild the freed dependency too."""
        import gc as _gc

        @ray_tpu.remote
        def base():
            return np.full(200_000, 3.0)

        @ray_tpu.remote
        def double(a):
            return a * 2

        x = base.remote()
        y = double.remote(x)
        assert ray_tpu.get(y)[0] == 6.0
        x_id = x.id()
        del x  # drop the only ref; GC frees x once deps release
        _gc.collect()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if x_id not in rt.directory:
                break
            time.sleep(0.05)
        assert x_id not in rt.directory  # x is gone
        rt.node.store.delete(y.id())     # now lose y's bytes too
        out = ray_tpu.get(y, timeout=60)
        assert out[0] == 6.0

    def test_lost_task_arg_triggers_reconstruction(self, rt):
        @ray_tpu.remote
        def base():
            return np.full(150_000, 5.0)

        @ray_tpu.remote
        def consume(a):
            return float(a.sum())

        x = base.remote()
        assert ray_tpu.get(consume.remote(x), timeout=30) == 750_000.0
        rt.node.store.delete(x.id())
        # Dispatch-side pin failure -> lineage rebuild -> resubmit.
        assert ray_tpu.get(consume.remote(x), timeout=60) == 750_000.0
