// C++ task/actor API demo against the cpp_gateway:
//   submit a registered task, call a named actor, fetch a tensor result
//   zero-copy.
//
//   g++ -std=c++17 -O2 -Icpp/include cpp/examples/gateway_demo.cc \
//       -o gateway_demo -lrt
//   ./gateway_demo <host> <port> <token>
#include <cstdio>
#include <cstdlib>

#include "ray_tpu/client.hpp"
#include "ray_tpu/tensor_writer.hpp"

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <host> <port> <token>\n", argv[0]);
    return 2;
  }
  ray_tpu::Client c(argv[1], std::atoi(argv[2]), argv[3]);

  // Plain task round trip.
  std::string ref = c.submit("add", "[2, 40]");
  ray_tpu::Result r = c.get(ref);
  if (!r.ok) return 3;
  std::printf("add -> %s\n", r.result.c_str());

  // Named-actor method calls keep state server-side.
  std::string a1 = c.call_actor("counter", "cppns", "bump", "[5]");
  std::string a2 = c.call_actor("counter", "cppns", "bump", "[7]");
  std::printf("bump -> %s then %s\n", c.get(a1).result.c_str(),
              c.get(a2).result.c_str());

  // Tensor result: shm hand-off, mapped zero-copy.
  std::string t = c.submit("make_tensor", "[64]");
  ray_tpu::Result tr = c.get(t);
  if (!tr.ok || tr.tensor_segment.empty()) return 4;
  {
    ray_tpu::TensorReader reader(tr.tensor_segment);
    const auto &v = reader.tensors.at(0);
    double sum = 0;
    const float *xs = reinterpret_cast<const float *>(v.data);
    for (uint64_t i = 0; i < v.nbytes / 4; ++i) sum += xs[i];
    std::printf("tensor sum -> %.1f\n", sum);
  }
  // The receiver owns the hand-off segment: unlink once consumed.
  shm_unlink(tr.tensor_segment.c_str());
  return 0;
}
