"""Mixture-of-experts: top-k routing + expert-parallel dispatch.

Absent from the reference (SURVEY §2.4 EP row: delegated to vLLM) — built
natively.  The expert dimension carries the ``expert`` logical axis, so
under the ``ep`` mesh axis GSPMD partitions the expert einsums and inserts
the token exchange implied by the dispatch.  The default dispatch is
capacity-based and SORTED (argsort assignments by expert + segment
offsets -> O(T*k) index arrays) rather than the GShard one-hot
``[T, X, C]`` tensor; dense (masked) dispatch remains available via
``capacity_factor=0`` for exactness tests.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class RoutingInfo(NamedTuple):
    combine_weights: jax.Array  # [B, S, X] softmax weights, zero off top-k
    router_probs: jax.Array     # [B, S, X] full softmax (for aux loss)
    expert_index: jax.Array     # [B, S, k]


def top_k_routing(x, router_w, k: int = 2,
                  router_noise: float = 0.0,
                  rng: Optional[jax.Array] = None) -> RoutingInfo:
    """x: [B, S, E]; router_w: [E, X] -> routing info."""
    logits = jnp.einsum("bse,ex->bsx", x.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    if router_noise > 0.0 and rng is not None:
        logits = logits + router_noise * jax.random.normal(
            rng, logits.shape, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    # Renormalize the selected experts' weights to sum to one.
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    combine = jnp.zeros_like(probs)
    combine = jnp.put_along_axis(
        combine, topi, topv, axis=-1, inplace=False) \
        if hasattr(jnp, "put_along_axis") else _scatter(combine, topi, topv)
    return RoutingInfo(combine, probs, topi)


def _scatter(zeros, idx, vals):
    one_hot = jax.nn.one_hot(idx, zeros.shape[-1], dtype=vals.dtype)
    return jnp.einsum("bskx,bsk->bsx", one_hot, vals)


def load_balancing_loss(info: RoutingInfo, num_experts: int) -> jax.Array:
    """Switch-transformer style aux loss."""
    me = jnp.mean(info.router_probs, axis=(0, 1))            # [X]
    ce = jnp.mean((info.combine_weights > 0).astype(jnp.float32), axis=(0, 1))
    return num_experts * jnp.sum(me * ce)


def capacity_dispatch(info: RoutingInfo, num_experts: int,
                      capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Build GShard-style dispatch/combine tensors with capacity dropping.

    Tokens are assigned slots within each expert in token order via a
    cumulative count; assignments beyond ``capacity`` are dropped (their
    contribution to the output is zero — the residual stream carries them).

    Returns (dispatch [T, X, C] one-hot float, combine [T, X, C]) over
    flattened tokens T = B*S.
    """
    B, S, X = info.combine_weights.shape
    k = info.expert_index.shape[-1]
    idx = info.expert_index.reshape(B * S, k)
    weights = info.combine_weights.reshape(B * S, X)

    counts = jnp.zeros((X,), jnp.int32)
    dispatch = jnp.zeros((B * S, X, capacity), jnp.float32)
    combine = jnp.zeros((B * S, X, capacity), jnp.float32)
    # Traced inside callers' jitted MoE layers; k is the top-k constant
    # (1-2), so the unrolled loop is two fused segments, not dispatch.
    for j in range(k):  # ray-tpu: noqa[RT506]
        oh = jax.nn.one_hot(idx[:, j], X, dtype=jnp.int32)     # [T, X]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]     # [T, X]
        keep = (pos < capacity) & (oh > 0)
        counts = counts + jnp.sum(oh * keep, axis=0)
        slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                              dtype=jnp.float32)               # [T, X, C]
        d_j = slot * keep[..., None].astype(jnp.float32)
        dispatch = dispatch + d_j
        w_j = jnp.take_along_axis(weights, idx[:, j:j + 1], axis=-1)
        combine = combine + d_j * w_j[..., None]
    return dispatch, combine


def sorted_dispatch(info: RoutingInfo, num_experts: int, capacity: int):
    """Sort-based token routing: assignments ordered by expert, with
    per-expert segment offsets giving each token its slot.

    Replaces the one-hot ``[T, X, C]`` dispatch tensor (O(T*X*C) memory
    and FLOPs) with O(T*k) index arrays: argsort assignments by expert,
    slot = position - expert segment start, drop slots >= capacity.

    Returns (tok_s [N], e_s [N], slot_s [N], w_s [N], keep [N]) over
    N = T*k assignments in expert-sorted order; ``slot_s`` equals
    ``capacity`` (out of range -> scatter mode 'drop') for dropped
    assignments.
    """
    B, S, X = info.combine_weights.shape
    k = info.expert_index.shape[-1]
    T = B * S
    N = T * k
    e_flat = info.expert_index.reshape(N)
    tok_flat = jnp.arange(N, dtype=jnp.int32) // k
    weights = info.combine_weights.reshape(T, X)
    w_flat = jnp.take_along_axis(
        weights, info.expert_index.reshape(T, k), axis=-1).reshape(N)
    order = jnp.argsort(e_flat, stable=True)  # token order within expert
    e_s = e_flat[order]
    tok_s = tok_flat[order]
    w_s = w_flat[order]
    counts = jnp.bincount(e_flat, length=num_experts)
    starts = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                              jnp.cumsum(counts)[:-1]])
    slot_s = jnp.arange(N, dtype=counts.dtype) - starts[e_s]
    keep = slot_s < capacity
    slot_s = jnp.where(keep, slot_s, capacity)  # OOB -> dropped by scatter
    return tok_s, e_s, slot_s, w_s, keep


def moe_layer(x, router_w, w_gate, w_up, w_down, k: int = 2,
              rng: Optional[jax.Array] = None,
              router_noise: float = 0.0,
              capacity_factor: float = 1.25) -> Tuple[jax.Array, jax.Array]:
    """SwiGLU expert MLPs with top-k routing.

    x: [B, S, E]; router_w: [E, X]; w_gate/w_up: [X, E, M]; w_down: [X, M, E].
    Returns (output [B, S, E], aux_loss scalar).

    The default is capacity-based sparse dispatch (sorted, see
    ``sorted_dispatch``): each expert processes at most
    ``ceil(k * T * capacity_factor / X)`` token slots, so expert FLOPs
    scale as top_k * capacity_factor / num_experts of dense; overflowing
    assignments are dropped (the residual stream carries them).  Under the
    ``ep`` mesh axis the per-expert buffers carry the ``expert`` logical
    axis, so GSPMD partitions the expert einsums and inserts the token
    exchange implied by the scatter/gather (GShard recipe with sorted
    instead of one-hot dispatch).

    ``capacity_factor == 0`` selects dense (masked) dispatch: every expert
    sees every token — exact, O(num_experts) FLOPs, useful for parity
    tests and tiny models.
    """
    import math

    X = router_w.shape[-1]
    info = top_k_routing(x, router_w, k=k, rng=rng,
                         router_noise=router_noise)
    if capacity_factor and capacity_factor > 0.0:
        B, S, E = x.shape
        T = B * S
        capacity = max(int(math.ceil(k * T * capacity_factor / X)), 1)
        tok_s, e_s, slot_s, w_s, keep = sorted_dispatch(info, X, capacity)
        xt = x.reshape(T, E)
        # Dispatch: gather token embeddings into per-expert slot buffers
        # (slot == capacity is out of bounds -> mode='drop').
        expert_in = jnp.zeros((X, capacity, E), x.dtype).at[
            e_s, slot_s].set(xt[tok_s], mode="drop")
        gate = jnp.einsum("xce,xem->xcm", expert_in, w_gate)
        up = jnp.einsum("xce,xem->xcm", expert_in, w_up)
        h = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("xcm,xme->xce", h, w_down)
        # Combine: weighted gather back to tokens (dropped slots read the
        # zero row via clamped slot? no — 'fill' gathers zeros for OOB).
        per_asgn = expert_out.at[e_s, slot_s].get(
            mode="fill", fill_value=0)                       # [N, E]
        contrib = per_asgn * (w_s * keep)[:, None].astype(per_asgn.dtype)
        out = jnp.zeros((T, E), contrib.dtype).at[tok_s].add(contrib)
        out = out.reshape(B, S, E)
    else:
        # Dense dispatch: compute all experts, weight by combine matrix.
        # Under the ep axis, each device computes only its expert shard
        # ("x" dim) and GSPMD reduces the combine einsum across ep.
        gate = jnp.einsum("bse,xem->bsxm", x, w_gate)
        up = jnp.einsum("bse,xem->bsxm", x, w_up)
        h = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("bsxm,xme->bsxe", h, w_down)
        out = jnp.einsum("bsxe,bsx->bse", expert_out,
                         info.combine_weights.astype(expert_out.dtype))
    return out.astype(x.dtype), load_balancing_loss(info, X)
