"""Mesh-reshape checkpoint restore: shard layout <-> index algebra.

A mesh-sharded save needs no special casing — ``checkpoint.format
.snapshot_tree`` decomposes jax Arrays through ``addressable_shards``,
recording the GLOBAL index of every chunk — so the work all lives on the
restore side: given the TARGET mesh's sharding layout, each process
computes the index slices its devices own (``process_index``), restores
only those byte ranges through the checkpoint index algebra, and
reassembles per-device arrays into global jax Arrays.  Saved-mesh shape
and target-mesh shape are independent: dp8 -> fsdp8, fsdp8 -> dp2xfsdp4,
pp2xfsdp4 -> fsdp8 all reduce to index intersection (the
``tests/test_train_mesh.py`` reshape matrix locks this down bit-exactly).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ...checkpoint import sharding as idx
from ...util import telemetry

#: Axis print order for descriptors ("dp2xfsdp4") — outer-to-inner, same
#: as parallel.mesh.CANONICAL_ORDER.
_DESC_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


def mesh_descriptor(mesh_or_axes) -> str:
    """Canonical short name of a mesh shape: axes > 1 in outer-to-inner
    order (``"dp2xfsdp4"``), ``"single"`` for an all-ones mesh."""
    if isinstance(mesh_or_axes, dict):
        axes = mesh_or_axes
    else:
        axes = dict(zip(mesh_or_axes.axis_names,
                        mesh_or_axes.devices.shape))
    parts = [f"{a}{axes[a]}" for a in _DESC_ORDER
             if int(axes.get(a, 1)) > 1]
    parts += [f"{a}{s}" for a, s in axes.items()
              if a not in _DESC_ORDER and int(s) > 1]
    return "x".join(parts) if parts else "single"


def sharding_tree(logical_tree, mesh, rules=None):
    """Pytree of logical-axis tuples -> pytree of NamedShardings on
    ``mesh`` (None leaves stay None: host-side scalars/objects)."""
    import jax

    from ...parallel.sharding import default_rules, named_sharding
    rules = rules or default_rules()
    return jax.tree.map(
        lambda ax: None if ax is None else named_sharding(mesh, ax, rules),
        logical_tree,
        is_leaf=lambda x: x is None or isinstance(x, tuple))


def process_index(sharding, global_shape) -> Optional[idx.Index]:
    """The (bounding-box) slice of a global array THIS process's devices
    own under ``sharding`` — the restore placement, so a process never
    reads checkpoint byte ranges outside its shard."""
    if not global_shape:
        return None
    boxes = [idx.index_from_slices(slices, global_shape)
             for slices in
             sharding.addressable_devices_indices_map(
                 tuple(int(d) for d in global_shape)).values()]
    if not boxes:
        return idx.full_index(global_shape)
    return tuple(
        (min(b[d][0] for b in boxes), max(b[d][1] for b in boxes))
        for d in range(len(global_shape)))


def _key_shardings(sharding_tree_) -> Dict[str, Any]:
    import jax

    from ...checkpoint.format import _key_str
    flat, _ = jax.tree_util.tree_flatten_with_path(
        sharding_tree_, is_leaf=lambda x: x is None or _is_sharding(x))
    return {_key_str(path): sh for path, sh in flat}


def _is_sharding(x) -> bool:
    return hasattr(x, "addressable_devices_indices_map")


def placement_for(sharding_tree_) -> Callable:
    """checkpoint ``placement`` callable from a sharding pytree: each
    leaf restores only the process-owned bounding box."""
    by_key = _key_shardings(sharding_tree_)
    def placement(key: str, global_shape) -> Optional[idx.Index]:
        sh = by_key.get(key)
        if sh is None or not global_shape:
            return None
        return process_index(sh, global_shape)
    return placement


def save_metrics(mesh, metrics: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Stamp the saving mesh's shape into checkpoint metrics so a later
    restore can tell a same-shape resume from a mesh reshape.  The
    ``"mesh"`` metrics key is RESERVED on mesh-active saves: the stamp
    is unconditional — a user value left in its place would make every
    restore's descriptor comparison misfire as a reshape."""
    out = dict(metrics or {})
    out["mesh"] = mesh_descriptor(mesh)
    return out


def restore_to_mesh(path: str, sharding_tree_, *,
                    loader: Optional[Callable] = None,
                    count_reshape: bool = True):
    """Restore a committed checkpoint onto a (possibly different) mesh.

    ``sharding_tree_``: pytree of NamedShardings (None leaves restore to
    host values unchanged) matching the saved tree's structure.
    ``loader(path, placement)`` overrides the raw restore (the train
    context passes its replica-aware WorkerCheckpointClient.load).
    ``count_reshape=False`` suppresses the reshape-counter bump — the
    trainer path counts once per GROUP (rank 0), not once per process.
    Returns a pytree of global jax Arrays laid out per the shardings.
    """
    import jax
    import numpy as np

    from ...checkpoint import format as ckpt_format

    manifest = ckpt_format.read_manifest(path)
    by_key = _key_shardings(sharding_tree_)
    placement = placement_for(sharding_tree_)
    if loader is not None:
        host = loader(path, placement)
    else:
        host = ckpt_format.restore_tree(path, placement=placement)

    saved_desc = (manifest.get("metrics") or {}).get("mesh")
    target_mesh = next((sh.mesh for sh in by_key.values()
                        if sh is not None), None)
    if count_reshape and isinstance(saved_desc, str) and \
            target_mesh is not None and \
            saved_desc != mesh_descriptor(target_mesh):
        telemetry.inc("ray_tpu_train_mesh_reshapes_total")

    flat, treedef = jax.tree_util.tree_flatten_with_path(host)
    leaves = []
    for hpath, block in flat:
        key = ckpt_format._key_str(hpath)
        sh = by_key.get(key)
        gshape_l = (manifest.get("leaves") or {}).get(key, {}) \
            .get("global_shape")
        if sh is None or gshape_l is None:
            leaves.append(block)
            continue
        gshape = tuple(int(d) for d in gshape_l)
        box = process_index(sh, gshape) or idx.full_index(gshape)
        block = np.asarray(block)
        per_dev = []
        for dev, slices in sh.addressable_devices_indices_map(
                gshape).items():
            didx = idx.index_from_slices(slices, gshape)
            rel = tuple(slice(lo - b0, hi - b0)
                        for (lo, hi), (b0, _) in zip(didx, box))
            per_dev.append(jax.device_put(
                np.ascontiguousarray(block[rel]), dev))
        leaves.append(jax.make_array_from_single_device_arrays(
            gshape, sh, per_dev))
    out = jax.tree_util.tree_unflatten(treedef, leaves)
    from .runtime import note_param_shard_bytes
    note_param_shard_bytes(out)
    return out
