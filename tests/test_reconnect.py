"""Retryable head connection: a dropped node<->head control connection
re-attaches under the same node identity within the grace window — no
task fails, workers and actors survive, buffered TaskDones replay.

Reference analog: src/ray/rpc/retryable_grpc_client.h (deadline/backoff
reconnect) + raylets re-attaching after GCS failover instead of dying
with the connection.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(head_num_cpus=0)
    c.add_node(num_cpus=2)
    c.wait_for_nodes(1)
    yield c
    c.shutdown()


class TestHeadReconnect:
    def test_drop_under_load_no_task_fails(self, cluster):
        rt = cluster.runtime

        @ray_tpu.remote(num_cpus=1)
        def work(i):
            time.sleep(0.15)
            return i * 2

        @ray_tpu.remote(num_cpus=1)
        class Keeper:
            def __init__(self):
                self.v = 0

            def bump(self):
                self.v += 1
                return self.v

        k = Keeper.remote()
        assert ray_tpu.get(k.bump.remote(), timeout=60) == 1

        node_ids = [n.node_id for n in rt.controller.alive_nodes()
                    if not n.is_head]
        assert len(node_ids) == 1
        nid = node_ids[0]

        refs = [work.remote(i) for i in range(30)]
        time.sleep(0.3)  # some tasks in flight on the node
        # Sever the control connection from the head side (network blip /
        # head hiccup): the node must re-attach, not die.
        proxy = rt.head_server.proxies[nid]
        proxy.conn.close()

        # Every task completes, none failed or was re-run spuriously.
        assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(30)]
        # The actor survived the blip with its state (same incarnation).
        assert ray_tpu.get(k.bump.remote(), timeout=60) == 2
        # Same node identity after re-attach; no second node appeared.
        after = [n.node_id for n in rt.controller.alive_nodes()
                 if not n.is_head]
        assert after == [nid]
        # More work schedules onto the re-attached node.
        assert ray_tpu.get(work.remote(100), timeout=60) == 200
