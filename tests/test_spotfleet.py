"""Goodput-driven autoscaling + spot-fleet elasticity.

Policy unit tests (pure decision logic), the seeded spot-market schedule
generator, the checked-in BENCH_spotfleet.json SLA gate, and the tier-1
smoke of ``bench.py --spec spotfleet --fast`` (bounded runtime).
"""

from __future__ import annotations

import json
import os
import sys

import pytest

from ray_tpu.autoscaler import (GoodputAutoscalePolicy,
                                GoodputPolicyConfig)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestGoodputPolicy:
    def test_prebuy_fires_once_per_victim(self):
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            default_node_type="spot"))
        d1 = p.decide([("n1", "spot")], pending=0, now=0.0)
        assert len(d1) == 1
        assert d1[0].reason == "prebuy" and d1[0].victim == "n1"
        assert d1[0].node_type == "spot" and d1[0].count == 1
        # The notice repeats every tick until the node dies; the buy
        # must not.
        assert p.decide([("n1", "spot")], pending=1, now=0.5) == []
        assert p.decide([("n1", "spot")], pending=0, now=1.0) == []
        # Victim died (notice gone); a NEW victim buys again.
        d2 = p.decide([("n2", None)], pending=0, now=2.0)
        assert len(d2) == 1 and d2[0].victim == "n2"
        # node_type falls back to the configured default.
        assert d2[0].node_type == "spot"

    def test_notice_storm_bounded_by_max_pending(self):
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            max_pending_prebuys=2))
        notices = [("a", None), ("b", None), ("c", None)]
        d = p.decide(notices, pending=0, now=0.0)
        assert len(d) == 2  # storm bound
        # Once those buys join (pending back to 0) the remaining victim,
        # still noticed, gets its replacement.
        d2 = p.decide([("c", None)], pending=0, now=1.0)
        assert len(d2) == 1 and d2[0].victim == "c"

    def test_cancelled_drain_can_rebuy_later(self):
        p = GoodputAutoscalePolicy(GoodputPolicyConfig())
        assert len(p.decide([("n1", None)], 0, now=0.0)) == 1
        # Notice vanishes (cancelled), then re-notices: buys again.
        assert p.decide([], 0, now=1.0) == []
        assert len(p.decide([("n1", None)], 0, now=2.0)) == 1

    def test_goodput_sag_buys_after_sustain_then_cooldown_gates(self):
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            goodput_floor=0.5, sustain_s=2.0, cooldown_s=10.0,
            window_s=60.0, default_node_type="spot"))
        p.observe_goodput({"productive_s": 1.0, "total_s": 10.0},
                          now=0.0)
        p.observe_goodput({"productive_s": 2.0, "total_s": 20.0},
                          now=1.0)
        # Windowed goodput 0.1 < floor, but not yet sustained.
        assert p.decide([], 0, now=1.0) == []
        p.observe_goodput({"productive_s": 3.0, "total_s": 30.0},
                          now=3.5)
        d = p.decide([], 0, now=3.5)
        assert len(d) == 1 and d[0].reason == "goodput"
        # Cooldown gates the next goodput buy.
        p.observe_goodput({"productive_s": 4.0, "total_s": 40.0},
                          now=5.0)
        assert p.decide([], 0, now=5.0) == []
        # ... until it expires (sag still sustained).
        p.observe_goodput({"productive_s": 5.0, "total_s": 55.0},
                          now=14.0)
        assert len(p.decide([], 0, now=14.0)) == 1

    def test_healthy_goodput_never_buys(self):
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            goodput_floor=0.5, sustain_s=0.0))
        p.observe_goodput({"productive_s": 9.0, "total_s": 10.0},
                          now=0.0)
        p.observe_goodput({"productive_s": 18.0, "total_s": 20.0},
                          now=1.0)
        assert p.decide([], 0, now=1.0) == []
        assert p.last_windowed_goodput == pytest.approx(0.9)

    def test_tracker_restart_resets_window(self):
        """A restarted GoodputTracker's cumulative counters reset; the
        negative deltas must start a fresh window, not a phantom sag."""
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            goodput_floor=0.9, sustain_s=0.0))
        p.observe_goodput({"productive_s": 50.0, "total_s": 60.0},
                          now=0.0)
        p.observe_goodput({"productive_s": 1.0, "total_s": 2.0},
                          now=1.0)  # new tracker
        assert p.windowed_goodput() is None
        assert p.decide([], 0, now=1.0) == []

    def test_sustained_sag_requires_continuity(self):
        """Goodput recovering above the floor resets the sustain clock."""
        p = GoodputAutoscalePolicy(GoodputPolicyConfig(
            goodput_floor=0.5, sustain_s=5.0, window_s=60.0))
        p.observe_goodput({"productive_s": 0.0, "total_s": 10.0},
                          now=0.0)
        p.observe_goodput({"productive_s": 0.0, "total_s": 12.0},
                          now=2.0)
        assert p.decide([], 0, now=2.0) == []  # sag starts
        # Recovery: productive jumps.
        p.observe_goodput({"productive_s": 10.0, "total_s": 22.0},
                          now=4.0)
        assert p.decide([], 0, now=4.0) == []  # sag cleared
        p.observe_goodput({"productive_s": 10.0, "total_s": 30.0},
                          now=6.0)
        assert p.decide([], 0, now=6.0) == []  # new sag, not sustained


class TestSpotFleetSchedule:
    def test_seed_determinism_and_jitter_bounds(self):
        from ray_tpu.devtools.chaos import ChaosSchedule
        a = ChaosSchedule.spot_fleet(seed=5, rate=0.4, horizon_s=50.0,
                                     deadline_range=(3.0, 7.0),
                                     no_notice_frac=0.2, add_rate=0.1)
        b = ChaosSchedule.spot_fleet(seed=5, rate=0.4, horizon_s=50.0,
                                     deadline_range=(3.0, 7.0),
                                     no_notice_frac=0.2, add_rate=0.1)
        assert [(e.at_s, e.action, e.deadline_s) for e in a.events] == \
            [(e.at_s, e.action, e.deadline_s) for e in b.events]
        kinds = [e.action for e in a.events]
        assert "preempt" in kinds
        assert "add_node" in kinds
        for e in a.events:
            assert 0.0 <= e.at_s < 50.0
            if e.action == "preempt":
                assert 3.0 <= e.deadline_s <= 7.0
                assert e.node is None  # symbolic: resolved at fire time
        # Events are time-ordered (the runner replays them in order).
        assert [e.at_s for e in a.events] == \
            sorted(e.at_s for e in a.events)

    def test_different_seeds_differ(self):
        from ray_tpu.devtools.chaos import ChaosSchedule
        a = ChaosSchedule.spot_fleet(seed=1, rate=0.4, horizon_s=50.0)
        b = ChaosSchedule.spot_fleet(seed=2, rate=0.4, horizon_s=50.0)
        assert [(e.at_s, e.action) for e in a.events] != \
            [(e.at_s, e.action) for e in b.events]


class TestSpotfleetBenchGate:
    """The checked-in BENCH_spotfleet.json is the elasticity-SLA
    baseline: it must hold its own SLA, and the --compare gate must
    treat its metrics as gateable (directions resolve)."""

    def _load(self):
        path = os.path.join(REPO_ROOT, "BENCH_spotfleet.json")
        assert os.path.exists(path), \
            "BENCH_spotfleet.json baseline missing"
        with open(path) as f:
            return path, json.load(f)

    def test_checked_in_baseline_holds_sla(self):
        _path, doc = self._load()
        sla = doc["sla"]
        assert sla["pass"] is True
        assert sla["floor_held"] and sla["beats_naive_goodput"]
        assert sla["lost_under_budget"] and sla["beats_naive_lost_steps"]
        assert sla["prebuy_before_deadline"]
        assert sla["multislice_survivor_committed"]
        assert sla["multislice_zero_lost_steps"]
        g = doc["churn"]["graceful"]
        n = doc["churn"]["naive"]
        assert g["scaled_goodput"] > n["scaled_goodput"]
        assert g["lost_steps"] <= n["lost_steps"]
        assert g["prebuy_total"] >= 1

    def test_compare_gate_covers_spotfleet_metrics(self):
        sys.path.insert(0, REPO_ROOT)
        import bench
        path, doc = self._load()
        out = bench.compare_bench(path, path, threshold=0.10)
        assert not out["regressions"]
        # The SLA booleans and goodput numbers actually gate (present in
        # the checked set), so a silently eroded rerun would fail.
        flat = bench._flatten_bench(doc)
        gated = [p for p in flat
                 if bench._metric_direction(p) is not None]
        assert any("scaled_goodput" in p for p in gated)
        assert any(p.endswith("sla.pass") for p in gated)


class TestAutoscalerStatusPublish:
    def test_reconcile_publishes_prebuy_status_to_kv(self):
        """The reconcile loop drops its live view (pending pre-buys,
        prebuy total, policy state) into the head KV under
        AUTOSCALER_KV_KEY — what `ray-tpu status` and
        /api/cluster/status print next to the goodput line."""
        import time

        import ray_tpu
        from ray_tpu.autoscaler import (AUTOSCALER_KV_KEY, Autoscaler,
                                        AutoscalerConfig,
                                        LocalSubprocessProvider,
                                        NodeTypeConfig)
        rt = ray_tpu.init(num_cpus=0, num_tpus=0, head_port=0,
                          cluster_token=b"sptok")
        try:
            provider = LocalSubprocessProvider(
                rt.head_server.address, b"sptok")
            asc = Autoscaler(rt, provider, AutoscalerConfig(
                node_types={"spot": NodeTypeConfig(
                    resources={"CPU": 1}, max_workers=2)},
                update_interval_s=0.2,
                policy=GoodputAutoscalePolicy(GoodputPolicyConfig(
                    default_node_type="spot"))))
            try:
                doc = None
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    raw = rt.ctl_kv_get(AUTOSCALER_KV_KEY)
                    if raw:
                        doc = json.loads(raw)
                        break
                    time.sleep(0.1)
                assert doc is not None, "autoscaler status never published"
                assert doc["pending_prebuys"] == 0
                assert doc["prebuy_total"] == 0
                assert doc["policy"]["goodput_floor"] == 0.5
                assert "nodes_by_type" in doc
                st = asc.status()
                assert st["pending_prebuys"] == 0
                assert st["policy"] is not None
            finally:
                asc.stop()
                provider.shutdown()
        finally:
            ray_tpu.shutdown()


class TestSpotfleetSmoke:
    # SLA axes that measure wall-clock goodput of the chaos scenarios.
    # On a loaded single-core host these dip without any code
    # regression (replacement boot + join competes with the training
    # loop for the same CPU), so they get ONE retry.  Everything else
    # in the SLA is deterministic and must hold on every attempt.
    _LOAD_SENSITIVE = ("floor_held",)

    def test_fast_bench_end_to_end(self, tmp_path):
        """`bench.py --spec spotfleet --fast` wired into tier-1 as a
        smoke: the full three-scenario run (churn graceful-vs-naive,
        pre-buy timing, 2-slice drain) in a SUBPROCESS with a hard wall
        bound, so even a pathological stall cannot eat the tier-1
        budget."""
        import subprocess

        out = str(tmp_path / "BENCH_spotfleet.json")
        code = (
            "import bench, json, sys\n"
            f"doc = bench.bench_spotfleet(fast=True, out_path={out!r})\n"
            "print('SLA_PASS', doc['sla']['pass'])\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="", XLA_FLAGS="")
        for attempt in (1, 2):
            if os.path.exists(out):
                os.remove(out)  # never judge a stale doc
            proc = subprocess.run(
                [sys.executable, "-u", "-c", code], cwd=REPO_ROOT,
                env=env, capture_output=True, text=True, timeout=420)
            # bench_spotfleet raises SystemExit(1) on an SLA fail but
            # still writes the doc; anything else (crash, no doc) is a
            # hard failure with no retry.
            assert os.path.exists(out), \
                f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n" \
                f"{proc.stderr[-4000:]}"
            with open(out) as f:
                doc = json.load(f)
            sla = doc["sla"]
            assert doc["churn"]["graceful"]["completed"]
            assert doc["churn"]["naive"]["completed"]
            assert sla["lost_under_budget"], sla
            assert sla["prebuy_before_deadline"], sla
            assert sla["multislice_survivor_committed"], sla
            assert sla["multislice_zero_lost_steps"], sla
            if sla["pass"]:
                assert proc.returncode == 0, \
                    f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n" \
                    f"{proc.stderr[-4000:]}"
                break
            failed = [k for k in self._LOAD_SENSITIVE if not sla[k]]
            assert failed, f"SLA failed outside load-sensitive axes: {sla}"
            assert attempt == 1, \
                f"goodput SLA failed on both attempts: {sla}"
        assert "SLA_PASS True" in proc.stdout
        assert doc["sla"]["pass"] is True


class TestSpotfleetSmokeQuick:
    def test_prebuy_timing_scenario(self):
        """The deterministic slice of the bench (declarative
        InstanceManager pre-buy) runs in tier-1 directly: replacement
        REQUESTED at notice time, RUNNING before the deadline."""
        sys.path.insert(0, REPO_ROOT)
        import bench
        out = bench._spotfleet_prebuy_timing()
        assert out["replacement_running_before_deadline"]
        assert out["notice_to_request_s"] is not None
        assert out["notice_to_request_s"] < 1.0
        assert out["notice_to_running_s"] < out["deadline_s"]
