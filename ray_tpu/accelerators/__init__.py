from .tpu import TPUAcceleratorManager

__all__ = ["TPUAcceleratorManager"]
