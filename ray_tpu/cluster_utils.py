"""Multi-node test harness: boot N node processes against one head.

Reference: ray.cluster_utils.Cluster (python/ray/cluster_utils.py:137,
add_node:204, remove_node:288) — the workhorse fixture for distributed
scheduling/failover tests, booting extra raylets as local processes.  Here
each added node is a ``NodeServer`` subprocess joining the in-process head
over TCP (the real join path, not a shortcut), so tests exercise
registration, remote dispatch, cross-node object transfer and node-death
handling exactly as a real multi-host cluster would.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["Cluster", "NodeHandle"]


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGKILL a node server together with all its worker processes."""
    import signal
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except Exception:
            pass


@dataclass
class NodeHandle:
    proc: subprocess.Popen
    num_cpus: float
    resources: Optional[Dict[str, float]]
    # Runtime node id (hex) once the join is observed — the address the
    # drain protocol / chaos harness target a node by.
    node_id: Optional[str] = None

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_num_cpus: float = 0,
                 head_resources: Optional[Dict[str, float]] = None,
                 token: Optional[bytes] = None):
        import ray_tpu
        self._token = token or os.urandom(8).hex().encode()
        self._nodes: list[NodeHandle] = []
        self.runtime = None
        if initialize_head:
            self.runtime = ray_tpu.init(
                num_cpus=head_num_cpus, num_tpus=0,
                resources=head_resources, head_port=0,
                cluster_token=self._token)
        self.address = self.runtime.head_server.address

    def add_node(self, num_cpus: float = 1, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 wait: bool = True, timeout: float = 30.0) -> NodeHandle:
        import json
        host, port = self.address
        cmd = [sys.executable, "-m", "ray_tpu._private.node_server_main",
               "--address", f"{host}:{port}",
               "--token", self._token.decode(),
               "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        env = dict(os.environ)
        # Joined nodes must not inherit a TPU claim from the test process.
        env.setdefault("RAY_TPU_TPU_CHIPS_PER_HOST_OVERRIDE", "0")
        # Own process group: killing a node takes its spawned workers with
        # it instead of leaving orphans that race the next test's runtime.
        before = self._alive_node_ids()
        proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        handle = NodeHandle(proc, num_cpus, resources)
        self._nodes.append(handle)
        if wait:
            self.wait_for_nodes(timeout=timeout)
            # Bind the runtime node id (the diff of the alive set) so the
            # handle can be drained/preempted by id.  Serial add_node
            # calls (the test-harness norm) make the diff unambiguous.
            new = self._alive_node_ids() - before
            if len(new) == 1:
                handle.node_id = next(iter(new))
        return handle

    def _alive_node_ids(self) -> set:
        return {n.node_id.hex()
                for n in self.runtime.controller.nodes.values()
                if n.alive and not n.is_head}

    def alive_node_count(self) -> int:
        return sum(1 for n in self.runtime.controller.nodes.values()
                   if n.alive)

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 30.0) -> None:
        """Block until `count` nodes (default: head + all added) are alive."""
        want = count if count is not None else 1 + len(
            [n for n in self._nodes if n.alive])
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.alive_node_count() >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster has {self.alive_node_count()} alive nodes, "
            f"wanted {want}")

    def remove_node(self, handle: NodeHandle, wait_dead: bool = True,
                    timeout: float = 15.0) -> None:
        """Hard-kill a node process (the node-failure injection primitive,
        reference: cluster_utils.remove_node:288)."""
        if handle.proc.poll() is None:
            _kill_group(handle.proc)
            handle.proc.wait(timeout=10)
        if handle in self._nodes:
            self._nodes.remove(handle)
        if wait_dead:
            want = 1 + sum(1 for n in self._nodes if n.alive)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if self.alive_node_count() <= want:
                    return
                time.sleep(0.05)

    def shutdown(self) -> None:
        import ray_tpu
        for h in list(self._nodes):
            if h.proc.poll() is None:
                _kill_group(h.proc)
        for h in list(self._nodes):
            try:
                h.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                h.proc.kill()
        self._nodes.clear()
        ray_tpu.shutdown()

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
