"""ray_tpu.rl — reinforcement learning library (the RLlib equivalent).

Reference: rllib/ — Algorithm/AlgorithmConfig (algorithms/algorithm.py:208),
RLModule (core/rl_module/rl_module.py:260), Learner/LearnerGroup
(core/learner/), EnvRunner(Group) (env/), replay buffers
(utils/replay_buffers/).  JAX-first: modules are pure-function pytrees,
learner updates are jit-compiled, and multi-learner data parallelism maps
to gradient averaging (psum on a TPU mesh; actor tree-mean on CPU).

Quick start::

    from ray_tpu.rl import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .training(lr=3e-4)
            .build_algo())
    for _ in range(10):
        print(algo.train()["env_runners"]["episode_return_mean"])
"""

from .algorithm import Algorithm, AlgorithmConfig
from .connectors import (ClipActions, Connector, ConnectorPipeline,
                         ObsFlatten, RewardClip,
                         FrameStack, LambdaConnector, MeanStdFilter)
from .dqn import DQN, DQNConfig
from .env import (CartPole, Env, Pendulum, StatelessGuess, TargetReach,
                  VectorEnv, make_env, register_env)
from .env_runner import EnvRunner, EnvRunnerGroup
from .impala import (APPO, APPOConfig, IMPALA, IMPALAConfig,
                     vtrace)
from .jax_env import JaxCartPoleVector
from .learner import JaxLearner, LearnerGroup
from .models import (CNNPolicyModule, CNNPolicySpec, GRUPolicyModule,
                     RecurrentPolicySpec)
from .multi_agent import (MultiAgentEnv, MultiAgentEnvRunner, MultiAgentPPO,
                          MultiAgentPPOConfig, MultiGuess)
from .iql import IQL, IQLConfig
from .offline import (BC, BCConfig, CQL, CQLConfig, MARWIL, MARWILConfig,
                      OfflineData, collect_from_env, save_parquet,
                      save_shard)
from .ppo import PPO, PPOConfig, compute_gae
from .replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from .rl_module import (ContinuousModuleSpec, DiscretePolicyModule,
                        GaussianPolicyModule, QModule, RLModuleSpec,
                        TwinQModule)
from .sac import SAC, SACConfig
from .tqc import TQC, TQCConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "IMPALA", "IMPALAConfig", "vtrace",
    "APPO", "APPOConfig",
    "BC", "BCConfig", "MARWIL", "MARWILConfig", "CQL", "CQLConfig",
    "IQL", "IQLConfig", "TQC", "TQCConfig",
    "OfflineData", "collect_from_env", "save_shard", "save_parquet",
    "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
    "MultiAgentPPOConfig", "MultiGuess",
    "Connector", "ConnectorPipeline", "MeanStdFilter", "FrameStack",
    "LambdaConnector", "ClipActions", "RewardClip", "ObsFlatten",
    "Env", "CartPole", "StatelessGuess", "Pendulum", "TargetReach",
    "VectorEnv", "JaxCartPoleVector", "make_env",
    "CNNPolicyModule", "CNNPolicySpec", "GRUPolicyModule",
    "RecurrentPolicySpec",
    "register_env", "EnvRunner", "EnvRunnerGroup", "JaxLearner",
    "LearnerGroup", "ReplayBuffer", "PrioritizedReplayBuffer",
    "DiscretePolicyModule", "GaussianPolicyModule", "TwinQModule",
    "ContinuousModuleSpec", "QModule", "RLModuleSpec", "compute_gae",
]
