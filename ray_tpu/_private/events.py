"""Task lifecycle event buffer feeding the state API and the timeline.

Reference: src/ray/core_worker/task_event_buffer.h:304 (TaskEventBuffer
batching task state transitions to the GCS) + src/ray/gcs/gcs_task_manager.h:97
(bounded task-event history served to the dashboard/state API) +
profile events (src/ray/core_worker/profile_event.h) that become the
``ray timeline`` chrome trace (python/ray/_private/state.py:471
chrome_tracing_dump).

Single-process control plane → one bounded buffer on the driver runtime; the
worker side reports through the existing TaskDone/note_task_running paths so
no extra RPC is needed.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# Task states, mirroring the reference's TaskStatus enum (common.proto).
PENDING_ARGS = "PENDING_ARGS_AVAIL"
SUBMITTED_TO_NODE = "SUBMITTED_TO_WORKER"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"


@dataclass
class TaskEvent:
    task_id: str
    name: str
    state: str = PENDING_ARGS
    type: str = "NORMAL_TASK"  # NORMAL_TASK | ACTOR_CREATION_TASK | ACTOR_TASK
    actor_id: Optional[str] = None
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    error_message: Optional[str] = None
    # state -> unix seconds of first entry into that state
    state_times: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "task_id": self.task_id, "name": self.name, "state": self.state,
            "type": self.type, "actor_id": self.actor_id,
            "node_id": self.node_id, "worker_id": self.worker_id,
            "error_message": self.error_message,
            "state_times": dict(self.state_times),
        }


@dataclass
class ProfileSpan:
    """A user/system span for the chrome-trace timeline."""
    name: str
    category: str
    start_s: float
    end_s: float
    pid: str  # row group (node / component)
    tid: str  # row (worker / thread)
    extra: Optional[Dict[str, Any]] = None


class TaskEventBuffer:
    """Bounded, insertion-ordered task event history (oldest evicted).

    ``record`` is on the per-task dispatch path (4 transitions per task),
    so it only appends a tuple to a deque — folding transitions into
    per-task TaskEvent state happens lazily at read time (reference:
    task_event_buffer.h batches transitions and ships them OFF the task
    path for the same reason)."""

    def __init__(self, max_events: int = 10000):
        self._max = max_events
        self._events: "OrderedDict[str, TaskEvent]" = OrderedDict()
        self._spans: List[ProfileSpan] = []
        self._lock = threading.Lock()
        self.num_dropped = 0
        from collections import deque
        self._pending: "deque" = deque()
        self._fold_at = max(1000, min(max_events * 2, 100_000))

    def record(self, task_id: str, state: str, *, name: Optional[str] = None,
               task_type: Optional[str] = None, actor_id: Optional[str] = None,
               node_id: Optional[str] = None, worker_id: Optional[str] = None,
               error_message: Optional[str] = None) -> None:
        # deque.append is thread-safe; no lock on the hot path.
        self._pending.append((task_id, state, time.time(), name, task_type,
                              actor_id, node_id, worker_id, error_message))
        if len(self._pending) >= self._fold_at:
            self._fold()

    def _fold(self) -> None:
        with self._lock:
            while True:
                try:
                    (task_id, state, now, name, task_type, actor_id,
                     node_id, worker_id, error_message) = \
                        self._pending.popleft()
                except IndexError:
                    break
                ev = self._events.get(task_id)
                if ev is None:
                    ev = TaskEvent(task_id=task_id, name=name or "")
                    self._events[task_id] = ev
                    if len(self._events) > self._max:
                        self._events.popitem(last=False)
                        self.num_dropped += 1
                if name:
                    ev.name = name
                if task_type:
                    ev.type = task_type
                if actor_id:
                    ev.actor_id = actor_id
                if node_id:
                    ev.node_id = node_id
                if worker_id:
                    ev.worker_id = worker_id
                if error_message is not None:
                    ev.error_message = error_message
                ev.state = state
                ev.state_times.setdefault(state, now)

    def add_span(self, span: ProfileSpan) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self._max:
                self._spans = self._spans[-self._max:]

    def snapshot(self, filters: Optional[Dict[str, Any]] = None,
                 limit: int = 10000) -> List[Dict[str, Any]]:
        if limit <= 0:
            return []
        self._fold()
        with self._lock:
            events = [e.to_dict() for e in self._events.values()]
        if filters:
            for k, v in filters.items():
                events = [e for e in events if e.get(k) == v]
        return events[-limit:]

    def summary(self) -> Dict[str, Dict[str, int]]:
        """name -> state -> count (reference: util/state summarize_tasks)."""
        self._fold()
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for ev in self._events.values():
                per = out.setdefault(ev.name or "<unnamed>", {})
                per[ev.state] = per.get(ev.state, 0) + 1
        return out

    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Chrome trace-event JSON (``ph: X`` complete events), one row per
        worker, one group per node — loadable in chrome://tracing and
        Perfetto (reference: _private/state.py:471 chrome_tracing_dump)."""
        trace: List[Dict[str, Any]] = []
        self._fold()
        with self._lock:
            events = list(self._events.values())
            spans = list(self._spans)
        for ev in events:
            start = ev.state_times.get(RUNNING)
            if start is None:
                continue
            end = (ev.state_times.get(FINISHED)
                   or ev.state_times.get(FAILED) or time.time())
            trace.append({
                "name": ev.name, "cat": "task", "ph": "X",
                "ts": start * 1e6, "dur": max(0.0, (end - start)) * 1e6,
                "pid": f"node:{(ev.node_id or 'driver')[:8]}",
                "tid": f"worker:{(ev.worker_id or '?')[:8]}",
                "args": {"task_id": ev.task_id, "state": ev.state},
            })
            # Queueing time as a lighter-weight slice.
            sub = ev.state_times.get(PENDING_ARGS)
            if sub is not None and start > sub:
                trace.append({
                    "name": f"{ev.name} (queued)", "cat": "scheduler",
                    "ph": "X", "ts": sub * 1e6, "dur": (start - sub) * 1e6,
                    "pid": "scheduler", "tid": "queue",
                    "args": {"task_id": ev.task_id},
                })
        for sp in spans:
            trace.append({
                "name": sp.name, "cat": sp.category, "ph": "X",
                "ts": sp.start_s * 1e6,
                "dur": max(0.0, sp.end_s - sp.start_s) * 1e6,
                "pid": sp.pid, "tid": sp.tid, "args": sp.extra or {},
            })
        return trace
