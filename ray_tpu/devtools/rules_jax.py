"""JAX correctness & performance lint rules (RT5xx).

The RT1-4xx families audit the control plane; this family audits the
accelerator hot path — the code that decides whether a step is fast.
Every rule is grounded in a bug class the repo has already paid for at
runtime (recompile churn, hidden device→host syncs, donated-buffer
reads) and pairs with the runtime half in :mod:`ray_tpu.devtools.
syncdebug` (``RAY_TPU_SYNC_DEBUG=1``), which catches at runtime what
the static rules cannot see.

* RT501 — Python control flow (``if``/``while``) on a traced value
  inside a jit-compiled function.  Traced-value flow runs over the
  per-function CFG (:mod:`ray_tpu.devtools.dataflow`): a name tainted
  in either branch of an ``if`` is tainted after the join.
* RT502 — implicit device→host sync per iteration: ``float()`` /
  ``.item()`` / ``bool()`` / ``np.asarray()`` / ``print`` on a device
  value inside a loop or comprehension.  One sync per *step* is the
  blessed batched pattern (see llm/engine.py's "ONE host sync"
  comments); one sync per *element* is the defect.
* RT503 — shape-unstable jit call site: a tracked jit called inside a
  loop on an array built from a list the same loop appends to — a new
  shape (and a recompile) every iteration.
* RT504 — donated-buffer read: an argument passed at a
  ``donate_argnums`` position of a tracked jit is read after the call
  without being rebound.
* RT505 — PRNG key reuse: the same key fed to two samplers (or to a
  sampler inside a loop) without an intervening ``split``/``fold_in``.
* RT506 — per-iteration op-by-op ``jnp`` dispatch outside any jit in a
  hot loop: each op is its own device round-trip; jit the body.

Shared here (and consumed by RT207 in rules_internal.py) is the
jax-context detection: which modules touch jax at all, which names are
jit-compiled functions, and with which static/donate argument
semantics.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from . import dataflow
from .lint import (Finding, ModuleContext, Rule, dotted, register,
                   walk_same_scope)

# --------------------------------------------------------------------------
# Shared jax-context detection
# --------------------------------------------------------------------------

#: Attribute reads that concretize nothing: static metadata available on
#: tracers and host handles alike (no trace-time branch, no host sync).
STATIC_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "itemsize", "nbytes", "sharding",
    "aval", "weak_type", "device", "devices", "is_deleted",
})

#: Builtins whose result on a traced/device value is static.
_STATIC_CALLS = frozenset({"len", "isinstance", "type", "id", "repr",
                           "getattr", "hasattr"})

#: jax.random functions that *derive* keys rather than consume entropy.
_KEY_DERIVERS = frozenset({"split", "fold_in", "PRNGKey", "key",
                           "key_data", "wrap_key_data", "clone"})


class _JaxContext:
    """Per-module jax facts, computed once and cached on the
    ModuleContext (every RT5xx rule and RT207 share one instance)."""

    def __init__(self, ctx: ModuleContext):
        self.jax_names: Set[str] = set()      # names bound to the jax module
        self.jnp_names: Set[str] = set()      # ... to jax.numpy
        self.np_names: Set[str] = set()       # ... to (host) numpy
        self.random_names: Set[str] = set()   # ... to jax.random
        self.jit_fn_names: Set[str] = set()   # names imported from jax
        self._scan_imports(ctx)
        # Lazy-import idiom (llm/engine.py holds `self._jax = jax`):
        # treat `<anything>._jax` attribute chains as the jax module.
        self.uses_jax = bool(self.jax_names or self.jnp_names or
                             self.random_names or self.jit_fn_names or
                             "._jax." in ctx.source)
        #: dotted call-site name -> jit kwargs ({"static_argnums": ...,
        #: "static_argnames": ..., "donate_argnums": ...}); covers
        #: `self._step = jax.jit(fn, ...)` and `g = jit(f)` bindings.
        self.jit_sites: Dict[str, Dict[str, object]] = {}
        #: function-def name -> jit kwargs, for defs that are
        #: jit-compiled either by decorator or by a jax.jit(<name>)
        #: wrap elsewhere in the module.
        self.jit_defs: Dict[str, Dict[str, object]] = {}
        if self.uses_jax:
            self._scan_jits(ctx)

    # -- imports -----------------------------------------------------------

    def _scan_imports(self, ctx: ModuleContext) -> None:
        for node in ctx.nodes(ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if alias.name == "jax":
                    self.jax_names.add(bound)
                elif alias.name == "jax.numpy":
                    self.jax_names.add("jax")
                    self.jnp_names.add(alias.asname or "jax.numpy")
                elif alias.name == "jax.random":
                    self.jax_names.add("jax")
                    self.random_names.add(alias.asname or "jax.random")
                elif alias.name == "numpy":
                    self.np_names.add(bound)
        for node in ctx.nodes(ast.ImportFrom):
            if node.module == "jax":
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "numpy":
                        self.jnp_names.add(bound)
                    elif alias.name == "random":
                        self.random_names.add(bound)
                    elif alias.name in ("jit", "pjit"):
                        self.jit_fn_names.add(bound)
                    else:
                        self.jax_names.add("jax")
            elif node.module == "jax.numpy":
                self.jnp_names.add("jax")  # marker: module uses jnp
            elif node.module and node.module.startswith("jax."):
                self.jax_names.add("jax")

    # -- jit bindings ------------------------------------------------------

    def _is_jit_expr(self, func: ast.AST) -> bool:
        name = dotted(func)
        if name is None:
            return False
        if name in self.jit_fn_names:
            return True
        last = name.rsplit(".", 1)[-1]
        if last not in ("jit", "pjit"):
            return False
        head = name.split(".", 1)[0]
        return head in self.jax_names or ".".join(
            name.split(".")[:-1]).endswith("_jax")

    @staticmethod
    def _jit_kwargs(call: ast.Call) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames",
                          "donate_argnums"):
                out[kw.arg] = _const_seq(kw.value)
        return out

    def _scan_jits(self, ctx: ModuleContext) -> None:
        # Decorated defs: @jax.jit / @jit / @partial(jax.jit, ...).
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for deco in fn.decorator_list:
                kwargs = self._decorator_jit_kwargs(deco)
                if kwargs is not None:
                    self.jit_defs[fn.name] = kwargs
                    self.jit_sites[fn.name] = kwargs
        # Assigned wraps: `target = jax.jit(fn, ...)`.
        for node in ctx.nodes(ast.Assign):
            value = node.value
            if not (isinstance(value, ast.Call) and
                    self._is_jit_expr(value.func)):
                continue
            kwargs = self._jit_kwargs(value)
            for target in node.targets:
                tname = dotted(target)
                if tname:
                    self.jit_sites[tname] = kwargs
            inner = value.args[0] if value.args else None
            # jax.jit(partial(fn, ...)) jits fn with leading args bound.
            if isinstance(inner, ast.Call) and \
                    (dotted(inner.func) or "").endswith("partial") and \
                    inner.args:
                inner = inner.args[0]
            iname = dotted(inner) if inner is not None else None
            if iname and "." not in iname:
                self.jit_defs[iname] = kwargs

    def _decorator_jit_kwargs(self,
                              deco: ast.AST) -> Optional[Dict[str, object]]:
        if self._is_jit_expr(deco):
            return {}
        if isinstance(deco, ast.Call):
            if self._is_jit_expr(deco.func):
                return self._jit_kwargs(deco)
            if (dotted(deco.func) or "").endswith("partial") and \
                    deco.args and self._is_jit_expr(deco.args[0]):
                return self._jit_kwargs(deco)
        return None

    # -- expression classification ----------------------------------------

    def is_device_call(self, call: ast.Call) -> bool:
        """Does this call produce a device value?  jnp.* / jax.* /
        jax.random.* ops and calls of tracked jit bindings."""
        name = dotted(call.func)
        if name is None:
            return False
        head = name.split(".", 1)[0]
        if head in self.jnp_names or head in self.random_names:
            return True
        if head in self.jax_names and "." in name:
            tail = name.split(".", 1)[1]
            # jax.device_get is the HOST transfer; jax.debug.print /
            # jax.tree_util etc. are not device values either.
            if tail.split(".")[0] not in ("debug", "tree_util", "tree",
                                          "config", "monitoring",
                                          "device_get"):
                return True
        if name in self.jit_sites:
            return True
        last = name.rsplit(".", 1)[-1]
        return last in ("device_put", "device_put_sharded",
                        "device_put_replicated")


def jax_context(ctx: ModuleContext) -> _JaxContext:
    cached = getattr(ctx, "_rt5_jax", None)
    if cached is None:
        cached = ctx._rt5_jax = _JaxContext(ctx)
    return cached


def module_uses_jax(ctx: ModuleContext) -> bool:
    """Shared jax-context gate (also RT207's scoping): does this module
    import jax / jax.numpy / jax.random (at module or function level),
    or hold the lazy ``self._jax`` module handle?"""
    return jax_context(ctx).uses_jax


def _const_seq(node: ast.AST) -> Optional[Tuple[object, ...]]:
    """Literal static/donate argnum specs: int/str constants and
    tuples/lists of them.  Non-literal (computed) specs -> None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, str)):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[object] = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and
                    isinstance(el.value, (int, str))):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _loops_in(fn: ast.AST) -> List[ast.AST]:
    return [n for n in walk_same_scope(fn)
            if isinstance(n, (ast.For, ast.While))]


def _is_jitted_def(fn: ast.AST, jc: _JaxContext) -> bool:
    return getattr(fn, "name", None) in jc.jit_defs


# --------------------------------------------------------------------------
# Traced-value taint over the CFG (RT501)
# --------------------------------------------------------------------------


def _expr_tainted(expr: Optional[ast.AST], tainted: Set[str]) -> bool:
    """Does evaluating ``expr`` yield a value derived from a tainted
    (traced) name?  Static metadata reads (``x.shape`` / ``len(x)`` /
    ``isinstance(x, ...)``) launder the taint — they are concrete at
    trace time."""
    if expr is None:
        return False
    if isinstance(expr, ast.Attribute) and expr.attr in STATIC_ATTRS:
        return False
    if isinstance(expr, ast.Call):
        fname = dotted(expr.func) or ""
        if fname in _STATIC_CALLS:
            return False
        args: List[ast.AST] = list(expr.args)
        args += [kw.value for kw in expr.keywords]
        if isinstance(expr.func, ast.Attribute):
            # method call on a tainted object (x.sum(), x.astype(...))
            args.append(expr.func.value)
        return any(_expr_tainted(a, tainted) for a in args)
    name = dotted(expr)
    if name is not None:
        return name in tainted
    return any(_expr_tainted(c, tainted)
               for c in ast.iter_child_nodes(expr))


def _assigned_names(target: ast.AST) -> List[str]:
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_assigned_names(el))
        return out
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    name = dotted(target)
    return [name] if name else []


def _transfer(node: dataflow.Node, tainted: Set[str]) -> Set[str]:
    """Forward taint transfer for one CFG node (may-be-traced)."""
    s = node.stmt
    if s is None:
        return tainted
    out = set(tainted)
    if node.kind == "loop-head" and isinstance(s, (ast.For, ast.AsyncFor)):
        names = _assigned_names(s.target)
        if _expr_tainted(s.iter, tainted):
            out.update(names)
        else:
            out.difference_update(names)
        return out
    if isinstance(s, ast.Assign):
        is_t = _expr_tainted(s.value, tainted)
        for t in s.targets:
            for name in _assigned_names(t):
                (out.add if is_t else out.discard)(name)
        return out
    if isinstance(s, ast.AnnAssign) and s.value is not None:
        is_t = _expr_tainted(s.value, tainted)
        for name in _assigned_names(s.target):
            (out.add if is_t else out.discard)(name)
        return out
    if isinstance(s, ast.AugAssign):
        names = _assigned_names(s.target)
        if _expr_tainted(s.value, tainted) or \
                any(n in tainted for n in names):
            out.update(names)
        return out
    if isinstance(s, (ast.With, ast.AsyncWith)):
        for item in s.items:
            if item.optional_vars is None:
                continue
            names = _assigned_names(item.optional_vars)
            if _expr_tainted(item.context_expr, tainted):
                out.update(names)
        return out
    return out


def _taint_with_cfg(fn: ast.AST, initial: Set[str]):
    """Fixpoint may-be-traced analysis over the per-function CFG:
    (cfg, node idx -> set of traced names *entering* that node).  A
    name tainted in either branch of an ``if`` is tainted after the
    join (union meet) — the property tests/test_lint_jax.py pins."""
    cfg = dataflow.build_cfg(fn)
    inset: Dict[int, Set[str]] = {n.idx: set() for n in cfg.nodes}
    inset[cfg.entry] = set(initial)
    work = [cfg.entry]
    while work:
        idx = work.pop()
        out = _transfer(cfg.nodes[idx], inset[idx])
        for succ in cfg.successors(idx):
            if not out <= inset[succ]:
                inset[succ] |= out
                work.append(succ)
    return cfg, inset


def traced_taint(fn: ast.AST,
                 initial: Set[str]) -> Dict[int, Set[str]]:
    """Public wrapper (the CFG taint unit tests drive this)."""
    return _taint_with_cfg(fn, initial)[1]


def _traced_params(fn: ast.AST, kwargs: Dict[str, object]) -> Set[str]:
    """Function params minus the static_argnums/static_argnames ones."""
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    static: Set[str] = set()
    nums = kwargs.get("static_argnums") or ()
    for n in nums:
        if isinstance(n, int) and 0 <= n < len(names):
            static.add(names[n])
    for n in kwargs.get("static_argnames") or ():
        if isinstance(n, str):
            static.add(n)
    if names and names[0] in ("self", "cls"):
        static.add(names[0])
    return {n for n in names if n not in static}


@register
class TracedControlFlow(Rule):
    id = "RT501"
    scope = "user"
    dataflow = True
    summary = "Python control flow on a traced value inside jit"
    rationale = ("Inside a jit-compiled function, arguments are tracers "
                 "without concrete values: `if x > 0:` either raises "
                 "ConcretizationTypeError or — when it slips through on "
                 "a weakly-typed path — freezes ONE branch into the "
                 "compiled program at trace time and silently drops the "
                 "other.  Branch on data with jax.lax.cond / jnp.where; "
                 "branch on *shape* freely (x.shape/x.ndim/len(x) are "
                 "static), or mark the argument static_argnums.")
    example_bad = (
        "@jax.jit\n"
        "def step(x):\n"
        "    if x.sum() > 0:      # traced value in a Python `if`\n"
        "        return x * 2\n"
        "    return x\n")
    example_good = (
        "@jax.jit\n"
        "def step(x):\n"
        "    return jnp.where(x.sum() > 0, x * 2, x)\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax:
            return
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            kwargs = jc.jit_defs.get(fn.name)
            if kwargs is None:
                continue
            initial = _traced_params(fn, kwargs)
            if not initial:
                continue
            cfg, taint = _taint_with_cfg(fn, initial)
            for node in cfg.nodes:
                s = node.stmt
                if node.kind == "stmt" and isinstance(s, ast.If):
                    test = s.test
                elif node.kind == "loop-head" and isinstance(s, ast.While):
                    test = s.test
                else:
                    continue
                name = _concretized_name(test, taint[node.idx])
                if name is None:
                    continue
                kind = "while" if isinstance(s, ast.While) else "if"
                yield ctx.finding(
                    self, s,
                    f"`{kind}` on traced value {name!r} inside "
                    f"jit-compiled `{fn.name}`: tracers have no concrete "
                    f"truth value — use jax.lax.cond/jnp.where, branch "
                    f"on shape/dtype (static), or mark it "
                    f"static_argnums")


def _concretized_name(test: ast.AST, tainted: Set[str]) -> Optional[str]:
    """First traced name whose concrete truth value the test needs, or
    None.  `x is None` / `x is not None` and `"key" in batch`
    comparisons are trace-time static (tracers are never None; pytree
    dict KEYS are concrete even when the values are traced) and
    exempt."""
    if isinstance(test, ast.Compare) and \
            all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in test.ops):
        return None
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            name = _concretized_name(v, tainted)
            if name:
                return name
        return None
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _concretized_name(test.operand, tainted)
    if _expr_tainted(test, tainted):
        for node in ast.walk(test):
            name = dotted(node)
            if name in tainted:
                return name
        return "<traced>"
    return None


# --------------------------------------------------------------------------
# Device-value taint (ordered, per scope) shared by RT502/RT503/RT504
# --------------------------------------------------------------------------


#: Host-coercion spellings RT502 flags (and syncdebug patches at
#: runtime): builtin casts, numpy materialization, and per-element
#: methods.
_COERCION_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_COERCION_METHODS = frozenset({"item", "tolist", "__array__"})


class _HotScan:
    """One ordered walk of a function body: propagates which names hold
    device values and reports host coercions at loop depth >= 1.
    Line-ordered like RT207 — cheaper than a fixpoint and right for the
    straight-line hot paths this targets."""

    def __init__(self, rule: Rule, ctx: ModuleContext, jc: _JaxContext,
                 fn: ast.AST):
        self.rule = rule
        self.ctx = ctx
        self.jc = jc
        self.fn = fn
        self.device: Set[str] = set()
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        for stmt in self.fn.body:
            self._stmt(stmt, 0)
        return self.findings

    # -- traversal ---------------------------------------------------------

    def _stmt(self, s: ast.AST, depth: int) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter, depth)
            names = _assigned_names(s.target)
            if self._tainted(s.iter):
                self.device.update(names)
            else:
                self.device.difference_update(names)
            for child in s.body + s.orelse:
                self._stmt(child, depth + 1)
            return
        if isinstance(s, ast.While):
            self._expr(s.test, depth)
            for child in s.body + s.orelse:
                self._stmt(child, depth + 1)
            return
        if isinstance(s, (ast.If,)):
            self._expr(s.test, depth)
            for child in s.body + s.orelse:
                self._stmt(child, depth)
            return
        if isinstance(s, ast.Try):
            for child in (s.body + s.orelse + s.finalbody +
                          [h for h in s.handlers]):
                if isinstance(child, ast.ExceptHandler):
                    for hs in child.body:
                        self._stmt(hs, depth)
                else:
                    self._stmt(child, depth)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._expr(item.context_expr, depth)
            for child in s.body:
                self._stmt(child, depth)
            return
        if isinstance(s, ast.Assign):
            self._expr(s.value, depth)
            is_dev = self._tainted(s.value)
            for t in s.targets:
                for name in _assigned_names(t):
                    (self.device.add if is_dev
                     else self.device.discard)(name)
            return
        if isinstance(s, ast.AnnAssign) and s.value is not None:
            self._expr(s.value, depth)
            is_dev = self._tainted(s.value)
            for name in _assigned_names(s.target):
                (self.device.add if is_dev else self.device.discard)(name)
            return
        if isinstance(s, ast.AugAssign):
            self._expr(s.value, depth)
            if self._tainted(s.value):
                self.device.update(_assigned_names(s.target))
            return
        if isinstance(s, ast.Return) and s.value is not None:
            self._expr(s.value, depth)
            return
        if isinstance(s, ast.Expr):
            self._expr(s.value, depth)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, depth)

    def _expr(self, e: Optional[ast.AST], depth: int) -> None:
        if e is None:
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            inner = set()
            for gen in e.generators:
                self._expr(gen.iter, depth)
                if self._tainted(gen.iter):
                    inner.update(_assigned_names(gen.target))
            saved = set(self.device)
            self.device |= inner
            body = [e.key, e.value] if isinstance(e, ast.DictComp) \
                else [e.elt]
            for b in body:
                self._expr(b, depth + 1)
            for gen in e.generators:
                for cond in gen.ifs:
                    self._expr(cond, depth + 1)
            self.device = saved
            return
        if isinstance(e, ast.Call):
            self._check_coercion(e, depth)
            for a in e.args:
                self._expr(a, depth)
            for kw in e.keywords:
                self._expr(kw.value, depth)
            if isinstance(e.func, ast.Attribute):
                self._expr(e.func.value, depth)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, depth)

    # -- classification ----------------------------------------------------

    def _tainted(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Call) and self.jc.is_device_call(e):
            return True
        if isinstance(e, ast.Attribute) and e.attr in STATIC_ATTRS:
            return False
        if isinstance(e, ast.Call):
            fname = dotted(e.func) or ""
            if fname in _STATIC_CALLS:
                return False
            if fname.rsplit(".", 1)[-1] == "device_get":
                return False  # the blessed explicit host transfer
            if fname.split(".", 1)[0] in self.jc.np_names:
                return False  # np.asarray(x) is the HOST copy
            args = list(e.args) + [kw.value for kw in e.keywords]
            if isinstance(e.func, ast.Attribute):
                args.append(e.func.value)
            return any(self._tainted(a) for a in args)
        name = dotted(e)
        if name is not None:
            return name in self.device
        return any(self._tainted(c) for c in ast.iter_child_nodes(e))

    def _check_coercion(self, call: ast.Call, depth: int) -> None:
        if depth < 1:
            return
        fname = dotted(call.func) or ""
        what: Optional[str] = None
        if fname in _COERCION_BUILTINS and len(call.args) == 1 and \
                self._tainted(call.args[0]):
            what = f"{fname}()"
        elif fname == "print" and any(self._tainted(a)
                                      for a in call.args):
            what = "print()"
        elif isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _COERCION_METHODS and \
                    self._tainted(call.func.value):
                what = f".{attr}()"
            elif attr in ("asarray", "array") and call.args and \
                    fname.split(".", 1)[0] in self.jc.np_names and \
                    self._tainted(call.args[0]):
                what = f"{fname}()"
        if what is None:
            return
        self.findings.append(self.ctx.finding(
            self.rule, call,
            f"implicit device→host sync per iteration: {what} on a "
            f"device value inside a loop blocks on the device every "
            f"pass — batch to ONE transfer outside the loop "
            f"(jax.device_get / a single np.asarray of the stacked "
            f"result)"))


@register
class HostSyncInHotLoop(Rule):
    id = "RT502"
    scope = "user"
    summary = "implicit device→host sync per loop iteration"
    rationale = ("float()/.item()/bool()/np.asarray()/print on a device "
                 "value blocks until the device catches up and ships "
                 "the value to host.  Once per step is the blessed "
                 "batched pattern; once per ELEMENT or per iteration "
                 "turns a fused device program into a sync storm — the "
                 "exact class the RAY_TPU_SYNC_DEBUG=1 tripwire counts "
                 "at runtime.  Stack on device, transfer once.")
    example_bad = (
        "metrics = train_step(params, batch)   # device dict\n"
        "return {k: float(v) for k, v in metrics.items()}  # N syncs\n")
    example_good = (
        "metrics = train_step(params, batch)\n"
        "host = jax.device_get(metrics)        # ONE sync\n"
        "return {k: float(v) for k, v in host.items()}\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax:
            return
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            if _is_jitted_def(fn, jc):
                continue  # inside jit these raise TracerError -> RT501
            yield from _HotScan(self, ctx, jc, fn).run()


@register
class ShapeUnstableJitCall(Rule):
    id = "RT503"
    scope = "user"
    summary = "shape-unstable jit call site in a loop"
    rationale = ("jax.jit specializes on argument SHAPES: calling a "
                 "jitted function on an array built from a list the "
                 "loop itself grows gives a new shape — and a full "
                 "recompile — every iteration (the recompile detector's "
                 "warm-site churn, seen statically).  Pad to a fixed "
                 "shape or bucket to powers of two (see llm/engine.py's "
                 "chunked prefill).")
    example_bad = (
        "buf = []\n"
        "for tok in stream:\n"
        "    buf.append(tok)\n"
        "    logits = decode_fn(jnp.array(buf))  # new shape each step\n")
    example_good = (
        "buf = np.zeros((MAX_LEN,), np.int32)\n"
        "for i, tok in enumerate(stream):\n"
        "    buf[i] = tok\n"
        "    logits = decode_fn(jnp.array(buf), i)  # fixed shape\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax or not jc.jit_sites:
            return
        array_ctors = jc.jnp_names | jc.np_names
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for loop in _loops_in(fn):
                appended = {
                    dotted(c.func.value)
                    for c in walk_same_scope(loop)
                    if isinstance(c, ast.Call) and
                    isinstance(c.func, ast.Attribute) and
                    c.func.attr in ("append", "extend") and
                    dotted(c.func.value)}
                if not appended:
                    continue
                for call in walk_same_scope(loop):
                    if not (isinstance(call, ast.Call) and
                            dotted(call.func) in jc.jit_sites):
                        continue
                    culprit = self._unstable_arg(call, appended,
                                                 array_ctors)
                    if culprit:
                        yield ctx.finding(
                            self, call,
                            f"shape-unstable jit call: "
                            f"{dotted(call.func)}(...{culprit}...) takes "
                            f"an array built from a list this loop "
                            f"appends to — a new shape (and recompile) "
                            f"every iteration; pad to a fixed shape or "
                            f"bucket sizes (power-of-two chunks)")

    @staticmethod
    def _unstable_arg(call: ast.Call, appended: Set[str],
                      ctors: Set[str]) -> Optional[str]:
        args = list(call.args) + [kw.value for kw in call.keywords]
        for arg in args:
            for node in ast.walk(arg):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted(node.func) or ""
                head, _, tail = fname.partition(".")
                if (head in ctors and
                        tail in ("array", "asarray", "stack")) or \
                        fname == "len":
                    inner = node.args[0] if node.args else None
                    iname = dotted(inner) if inner is not None else None
                    if iname in appended:
                        return f"{fname}({iname})"
        return None


@register
class DonatedBufferRead(Rule):
    id = "RT504"
    scope = "user"
    summary = "donated buffer read after a donate_argnums call"
    rationale = ("donate_argnums hands the argument's device buffer to "
                 "the compiled computation for reuse: after the call "
                 "the old array is DELETED — touching it raises 'Array "
                 "has been deleted' (or, on backends that alias, reads "
                 "garbage).  Rebind the name from the call's result "
                 "(the idiom: `params, state = step(params, state)`), "
                 "or drop the donation.")
    example_bad = (
        "step = jax.jit(train_step, donate_argnums=(0,))\n"
        "new_params = step(params, batch)\n"
        "log_norm(params)            # params' buffer was donated\n")
    example_good = (
        "step = jax.jit(train_step, donate_argnums=(0,))\n"
        "params = step(params, batch)   # rebind over the donation\n"
        "log_norm(params)\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax:
            return
        donating = {name: kw["donate_argnums"]
                    for name, kw in jc.jit_sites.items()
                    if kw.get("donate_argnums")}
        if not donating:
            return
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            yield from self._check_scope(ctx, jc, fn, donating)

    def _check_scope(self, ctx: ModuleContext, jc: _JaxContext,
                     fn: ast.AST, donating) -> Iterator[Finding]:
        calls: List[Tuple[ast.Call, List[str]]] = []
        for node in walk_same_scope(fn):
            if not (isinstance(node, ast.Call) and
                    dotted(node.func) in donating):
                continue
            nums = donating[dotted(node.func)]
            donated = [dotted(node.args[i]) for i in nums
                       if isinstance(i, int) and i < len(node.args) and
                       dotted(node.args[i])]
            if donated:
                calls.append((node, donated))
        if not calls:
            return
        # Line-ordered kill set: assignments to a name end its window.
        assigns: Dict[str, List[int]] = {}
        reads: Dict[str, List[Tuple[int, ast.AST]]] = {}
        for node in walk_same_scope(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for name in _assigned_names(t):
                        assigns.setdefault(name, []).append(node.lineno)
            name = dotted(node)
            if name and isinstance(getattr(node, "ctx", None), ast.Load):
                reads.setdefault(name, []).append((node.lineno, node))
        for call, donated in calls:
            for name in donated:
                rebind = min((ln for ln in assigns.get(name, ())
                              if ln >= call.lineno), default=None)
                for line, node in reads.get(name, ()):
                    if line <= call.lineno:
                        continue
                    if rebind is not None and line >= rebind:
                        continue
                    yield ctx.finding(
                        self, node,
                        f"{name!r} read after its buffer was donated to "
                        f"{dotted(call.func)} (donate_argnums, line "
                        f"{call.lineno}): the donated array is deleted "
                        f"by the call — rebind {name!r} from the "
                        f"result before reading it",
                        anchors=(call,))
                    break  # one finding per donated name per call


@register
class PrngKeyReuse(Rule):
    id = "RT505"
    scope = "user"
    summary = "PRNG key reused without split"
    rationale = ("jax.random is splittable-counter based: feeding the "
                 "SAME key to two samplers (or to one sampler every "
                 "loop iteration) yields identical 'random' numbers — "
                 "correlated dropout masks, identical exploration "
                 "noise, sharding-variant init.  split() before every "
                 "consumption: `key, sub = jax.random.split(key)` and "
                 "sample with `sub`.")
    example_bad = (
        "key = jax.random.PRNGKey(0)\n"
        "noise_a = jax.random.normal(key, shape)\n"
        "noise_b = jax.random.normal(key, shape)  # == noise_a\n")
    example_good = (
        "key = jax.random.PRNGKey(0)\n"
        "key, sub = jax.random.split(key)\n"
        "noise_a = jax.random.normal(sub, shape)\n"
        "key, sub = jax.random.split(key)\n"
        "noise_b = jax.random.normal(sub, shape)\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax:
            return
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            yield from self._check_scope(ctx, jc, fn)

    def _random_call(self, jc: _JaxContext,
                     node: ast.AST) -> Optional[str]:
        """'split'/'fold_in'/sampler name for a jax.random.* call."""
        if not isinstance(node, ast.Call):
            return None
        name = dotted(node.func)
        if not name:
            return None
        head, _, tail = name.partition(".")
        if head in jc.random_names and "." not in tail and tail:
            return tail
        if head in jc.jax_names and tail.startswith("random.") and \
                tail.count(".") == 1:
            return tail.split(".")[1]
        return None

    def _check_scope(self, ctx: ModuleContext, jc: _JaxContext,
                     fn: ast.AST) -> Iterator[Finding]:
        uses: Dict[str, List[Tuple[int, ast.Call]]] = {}
        freshened: Dict[str, List[int]] = {}
        loops = [(lp.lineno, getattr(lp, "end_lineno", lp.lineno), lp)
                 for lp in _loops_in(fn)]
        for node in walk_same_scope(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for name in _assigned_names(t):
                        freshened.setdefault(name, []).append(node.lineno)
                continue
            kind = self._random_call(jc, node)
            if kind is None or kind in _KEY_DERIVERS:
                continue
            args = list(node.args) + \
                [kw.value for kw in node.keywords if kw.arg == "key"]
            if not args:
                continue
            key = dotted(args[0])
            if key:
                uses.setdefault(key, []).append((node.lineno, node))
        for key, sites in uses.items():
            sites.sort()
            fresh = sorted(freshened.get(key, ()))
            # Case 1: two consumptions with no rebind between.
            prev_line = None
            flagged = False
            for line, node in sites:
                if prev_line is not None and not any(
                        prev_line < ln <= line for ln in fresh):
                    yield ctx.finding(
                        self, node,
                        f"PRNG key {key!r} reused (also consumed on "
                        f"line {prev_line}): identical keys give "
                        f"identical samples — `{key}, sub = jax.random."
                        f"split({key})` before each use")
                    flagged = True
                    break
                prev_line = line
            if flagged:
                continue
            # Case 2: consumed inside a loop without a per-iteration
            # refresh of the key in that same loop.
            for line, node in sites:
                loop = next((lp for s, e, lp in loops if s <= line <= e),
                            None)
                if loop is None:
                    continue
                s, e = loop.lineno, getattr(loop, "end_lineno",
                                            loop.lineno)
                if any(s <= ln <= e for ln in fresh):
                    continue
                if key in _assigned_names(getattr(loop, "target",
                                                  ast.Tuple(elts=[]))):
                    continue
                yield ctx.finding(
                    self, node,
                    f"PRNG key {key!r} consumed every iteration of the "
                    f"loop at line {s} without a split: each pass "
                    f"samples the SAME numbers — split or fold_in the "
                    f"key inside the loop")
                break


@register
class OpByOpDispatchInLoop(Rule):
    id = "RT506"
    scope = "user"
    summary = "per-iteration op-by-op jnp dispatch outside jit"
    rationale = ("Outside jit every jnp op is its own dispatch: a hot "
                 "loop running several ops per iteration pays Python "
                 "dispatch + executable launch per OP per STEP, and "
                 "nothing fuses.  Wrap the loop body in a jitted "
                 "function (one compiled program per iteration) or "
                 "lift the whole loop into jax.lax.scan/fori_loop.")
    example_bad = (
        "for batch in stream:\n"
        "    h = jnp.dot(batch, w1)\n"
        "    h = jnp.tanh(h + b1)\n"
        "    out = jnp.dot(h, w2)      # 3+ dispatches every pass\n")
    example_good = (
        "@jax.jit\n"
        "def fwd(batch, w1, b1, w2):\n"
        "    return jnp.dot(jnp.tanh(jnp.dot(batch, w1) + b1), w2)\n"
        "for batch in stream:\n"
        "    out = fwd(batch, w1, b1, w2)  # one compiled program\n")

    #: jnp op calls per loop body before the loop counts as op-by-op
    #: hot (1-2 ops is often glue around an already-jitted call).
    THRESHOLD = 3

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        jc = jax_context(ctx)
        if not jc.uses_jax or not jc.jnp_names:
            return
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            if _is_jitted_def(fn, jc):
                continue  # traced once, not dispatched per iteration
            for loop in _loops_in(fn):
                ops: List[str] = []
                for node in walk_same_scope(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted(node.func) or ""
                    head, _, tail = name.partition(".")
                    if head in jc.jnp_names and tail and \
                            not tail.startswith(("asarray", "array")):
                        ops.append(name)
                if len(ops) < self.THRESHOLD:
                    continue
                distinct = sorted(set(ops))
                shown = ", ".join(distinct[:4])
                yield ctx.finding(
                    self, loop,
                    f"op-by-op dispatch in a hot loop: {len(ops)} jnp "
                    f"op calls ({shown}{', ...' if len(distinct) > 4 else ''}) "
                    f"dispatch individually every iteration outside "
                    f"jit — jit the body or lift the loop into "
                    f"jax.lax.scan")
