"""MeshConfig: the declarative mesh shape a train worker group forms.

Carried on ``ScalingConfig.mesh_config`` and resolved against the ACTUAL
world size at every group (re)formation, so elastic resizes re-form the
mesh at a new shape instead of refusing ("a live mesh cannot be resized"
stays true — resize = teardown + re-form + resharding restore).

Axis semantics follow ``parallel.mesh.MeshSpec``: sizes are per named
axis (dp/fsdp/tp/sp/ep/pp), at most one axis may be ``-1`` ("absorb the
remaining devices"), and ``auto=True`` ignores the explicit sizes and
factorizes the device count as dp x fsdp with fsdp the largest divisor
<= 8 (one host's ICI domain; dp rides the slower DCN-most axis).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional

from ...parallel.mesh import CANONICAL_ORDER, MeshSpec

_AXIS_RE = re.compile(r"^(dp|fsdp|tp|sp|ep|pp)(-1|\d+)$")

#: Largest per-host axis the auto factorization assigns to fsdp.
_AUTO_FSDP_MAX = 8


@dataclass
class MeshConfig:
    """Mesh shape for the train worker group (``ScalingConfig.mesh_config``).

    ``devices_per_worker`` is the per-process device count: TPU chips per
    worker, or forced XLA host-platform devices on the CPU substrate
    (the controller injects ``--xla_force_host_platform_device_count``
    into each worker's env so tier-1 and the bench exercise real
    multi-device meshes).  ``rules`` overrides logical-axis sharding
    rules by name (e.g. ``{"embed": "tp"}``) on top of
    ``parallel.sharding.default_rules``.
    """
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    devices_per_worker: int = 1
    auto: bool = False
    rules: Optional[Dict[str, object]] = None

    @classmethod
    def parse(cls, text: str, devices_per_worker: int = 1) -> "MeshConfig":
        """``"dp2xfsdp4"`` / ``"fsdp8"`` / ``"auto"`` -> MeshConfig."""
        text = (text or "").strip().lower()
        if text in ("auto", ""):
            return cls(auto=True, devices_per_worker=devices_per_worker)
        sizes: Dict[str, int] = {}
        for token in text.split("x"):
            m = _AXIS_RE.match(token)
            if m is None:
                raise ValueError(
                    f"bad mesh axis token {token!r} in {text!r} "
                    f"(expected e.g. dp2xfsdp4, axes "
                    f"{'/'.join(CANONICAL_ORDER)})")
            axis, size = m.group(1), int(m.group(2))
            if axis in sizes:
                raise ValueError(f"mesh axis {axis!r} repeated in {text!r}")
            sizes[axis] = size
        return cls(devices_per_worker=devices_per_worker, **sizes)

    def axis_sizes(self) -> Dict[str, int]:
        return {"dp": self.dp, "fsdp": self.fsdp, "tp": self.tp,
                "sp": self.sp, "ep": self.ep, "pp": self.pp}

    # -- resolution ---------------------------------------------------------

    def spec_for(self, total_devices: int,
                 num_slices: int = 1) -> MeshSpec:
        """Resolve to a concrete MeshSpec over ``total_devices`` (raises
        ValueError when the shape cannot tile them)."""
        if total_devices < 1:
            raise ValueError(f"total_devices must be >= 1, got "
                             f"{total_devices}")
        if self.auto:
            spec = _auto_spec(total_devices, num_slices)
        else:
            spec = MeshSpec(num_slices=num_slices,
                            **self.axis_sizes()).resolved(total_devices)
        if num_slices > 1 and spec.dp % num_slices:
            raise ValueError(
                f"dp axis ({spec.dp}) must be divisible by num_slices "
                f"({num_slices}): the outermost dp axis maps slice-major "
                f"onto the DCN fabric")
        return spec

    def valid_world(self, num_workers: int, num_slices: int = 1) -> bool:
        """Can a group of ``num_workers`` processes tile this mesh?"""
        if num_workers < 1:
            return False
        try:
            self.spec_for(num_workers * self.devices_per_worker,
                          num_slices)
        except ValueError:
            return False
        return True

    def nearest_valid_world(self, target: int, floor: int = 1,
                            ceiling: Optional[int] = None,
                            num_slices: int = 1) -> Optional[int]:
        """Largest valid world size <= ``target`` (>= ``floor``); when no
        smaller world tiles the mesh, the smallest valid one in
        (target, ceiling].  None when nothing in range is valid.

        This is what keeps elastic sizing from forming a group the mesh
        cannot tile: a drain that would leave 3 workers on a
        fsdp-even mesh downsizes to 2 instead.
        """
        for w in range(min(target, ceiling or target), floor - 1, -1):
            if self.valid_world(w, num_slices):
                return w
        if ceiling is not None:
            for w in range(target + 1, ceiling + 1):
                if self.valid_world(w, num_slices):
                    return w
        return None

    def validate_scaling(self, scaling) -> None:
        """Fail fast at trainer construction when the configured worker
        range contains no world size this mesh can tile."""
        if self.devices_per_worker < 1:
            raise ValueError("devices_per_worker must be >= 1")
        num_slices = getattr(scaling, "num_slices", 1)
        if getattr(scaling, "elastic", False):
            lo = scaling.min_workers or 1
            hi = scaling.max_workers or max(scaling.num_workers, lo)
            if self.nearest_valid_world(hi, floor=lo,
                                        num_slices=num_slices) is None:
                raise ValueError(
                    f"mesh {self.axis_sizes()} (x{self.devices_per_worker} "
                    f"devices/worker) tiles no world size in "
                    f"[{lo}, {hi}]")
        else:
            # Raises with the tiling arithmetic when invalid.
            self.spec_for(scaling.num_workers * self.devices_per_worker,
                          num_slices)

    def sharding_rules(self):
        """default_rules() with this config's per-logical-name overrides."""
        return rules_with_overrides(self.rules)


def rules_with_overrides(overrides: Optional[Dict[str, object]]):
    """default_rules() + per-logical-name overrides — the ONE merge
    implementation shared by MeshConfig and the worker TrainContext
    (ranks resolving rules differently would shard differently)."""
    from ...parallel.sharding import default_rules
    rules = default_rules()
    if overrides:
        rules = rules.replace(**{k: _as_axes(v)
                                 for k, v in overrides.items()})
    return rules


def _as_axes(v):
    """JSON/env-safe rule values: lists arrive where tuples are meant."""
    return tuple(v) if isinstance(v, list) else v


def _auto_spec(total_devices: int, num_slices: int) -> MeshSpec:
    """dp x fsdp factorization: fsdp = largest divisor <= 8 (ICI-sized),
    dp absorbs the rest (and must carry the slice axis when
    num_slices > 1)."""
    fsdp = 1
    for cand in range(min(_AUTO_FSDP_MAX, total_devices), 0, -1):
        if total_devices % cand == 0:
            # dp must stay divisible by num_slices for the DCN mapping.
            if num_slices > 1 and (total_devices // cand) % num_slices:
                continue
            fsdp = cand
            break
    return MeshSpec(dp=total_devices // fsdp, fsdp=fsdp,
                    num_slices=num_slices)
