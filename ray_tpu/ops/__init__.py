"""TPU ops layer: pallas kernels + SPMD attention/MoE primitives.

No reference analog (SURVEY §2.4: SP/CP/EP are absent in the reference,
delegated to vLLM/DeepSpeed).  Built natively here:

- ``attention``     — causal (GQA) attention; pallas flash kernel on TPU,
                      jnp reference elsewhere
- ``ring_attention``— context parallelism over an ICI ring
                      (K/V rotate via ppermute, online-softmax accumulation)
- ``ulysses``       — sequence<->head all-to-all context parallelism
- ``moe``           — top-k routed mixture-of-experts with expert-parallel
                      dispatch
- ``norms``/``rope``/``swiglu`` — fused-friendly elementwise building blocks
"""

from .norms import rms_norm
from .rope import apply_rope, rope_frequencies
from .attention import attention, flash_attention, reference_attention
from .ring_attention import ring_attention
from .ulysses import ulysses_attention
from .moe import moe_layer, top_k_routing

__all__ = [
    "rms_norm", "apply_rope", "rope_frequencies",
    "attention", "flash_attention", "reference_attention",
    "ring_attention", "ulysses_attention", "moe_layer", "top_k_routing",
]
