"""SLO-aware admission control + the disaggregated serving plane.

Reference analog: the production pattern the reference's serving stack
points at (python/ray/llm/_internal/serve/ wrapping vLLM) and the
DistServe/Splitwise split the industry converged on — separate prefill
and decode tiers with KV handoff, fronted by admission control so
overload degrades into FAST RETRIABLE REJECTIONS instead of timeout
storms.

Three pieces:

* :class:`AdmissionController` — pure decision logic: per-class token
  budgets, bounded queues, and backpressure driven by the decode
  engine's live KV-occupancy/queue telemetry.  A shed is an
  :class:`~ray_tpu.serve.OverloadError` (retriable), never a silent
  timeout.
* :class:`DisaggServer` — one serving plane: router + dispatcher +
  decode driver.  ``mode="disagg"`` runs a :class:`PrefillWorker` and
  hands KV to the decode engine through the shm object store;
  ``mode="chunked"`` is the disagg-off fallback (single engine, long
  prompts sliced across decode steps); ``mode="inline"`` is the legacy
  stall-everything baseline, kept for A/B benching.
* :func:`build_disagg_deployment` — the plane as a serve deployment
  (``DisaggServer.__call__`` is the replica entry point).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..._private import sanitizer
from ...serve.api import OverloadError
from ...util import telemetry, tracing
from ..engine import InferenceEngine, SamplingParams
from .handoff import export_handoff, import_handoff
from .prefill import PrefillWorker


@dataclass
class RequestClass:
    """Admission envelope for one traffic class."""

    name: str = "default"
    #: Max in-flight tokens (prompt + max_tokens, summed over admitted
    #: but unfinished requests).  None = unbounded.
    token_budget: Optional[int] = None
    max_queue_depth: int = 64
    #: A request still queued this long after submit is shed — it would
    #: blow its TTFT SLO anyway, so fail fast and retriably.
    queue_deadline_s: float = 10.0


@dataclass
class AdmissionConfig:
    classes: Dict[str, RequestClass] = field(
        default_factory=lambda: {"default": RequestClass()})
    #: With decode KV occupancy at/above this AND work already waiting,
    #: new arrivals shed instead of joining a queue that cannot drain.
    kv_high_watermark: float = 0.97

    def class_for(self, name: str) -> RequestClass:
        rc = self.classes.get(name)
        if rc is None:
            rc = self.classes.get("default")
        return rc if rc is not None else RequestClass()


class AdmissionController:
    """Shed/admit decisions; DisaggServer feeds it live engine load."""

    def __init__(self, cfg: AdmissionConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._queued: Dict[str, int] = {}
        self._inflight_tokens: Dict[str, int] = {}
        #: EWMA of observed queue wait at dequeue (deadline feasibility).
        self._wait_ewma: Optional[float] = None

    def try_admit(self, clazz: str, total_tokens: int,
                  load: Dict[str, Any]) -> Optional[str]:
        """None = admitted (queue slot + token budget charged); else the
        shed reason."""
        rc = self.cfg.class_for(clazz)
        with self._lock:
            q = self._queued.get(clazz, 0)
            if q >= rc.max_queue_depth:
                return "queue_full"
            if rc.token_budget is not None and \
                    self._inflight_tokens.get(clazz, 0) + total_tokens \
                    > rc.token_budget:
                return "class_budget"
            if load.get("kv_occupancy", 0.0) >= self.cfg.kv_high_watermark \
                    and (q or load.get("waiting", 0)):
                return "backpressure"
            # Deadline feasibility: when requests currently LEAVING the
            # queue already waited past this class's deadline and work
            # is still queued ahead, a new arrival is hopeless — it
            # would age to its deadline and shed at dequeue anyway.
            # Shed it NOW (retriable, microseconds after submit)
            # instead of parking it to die.  Guarded on a non-empty
            # queue so a stale EWMA from a past saturation burst never
            # sheds the first arrivals of a fresh one.
            if self._wait_ewma is not None \
                    and self._wait_ewma > rc.queue_deadline_s \
                    and sum(self._queued.values()) > 0:
                return "deadline_infeasible"
            self._queued[clazz] = q + 1
            self._inflight_tokens[clazz] = \
                self._inflight_tokens.get(clazz, 0) + total_tokens
        self._set_depth_gauge(clazz)
        return None

    def note_dequeued(self, clazz: str) -> None:
        with self._lock:
            self._queued[clazz] = max(0, self._queued.get(clazz, 0) - 1)
        self._set_depth_gauge(clazz)

    def note_queue_wait(self, wait_s: float) -> None:
        """Dispatcher-observed queue wait for one dequeued request —
        feeds the admission-time deadline-feasibility estimate."""
        with self._lock:
            self._wait_ewma = wait_s if self._wait_ewma is None \
                else 0.7 * self._wait_ewma + 0.3 * wait_s

    def note_finished(self, clazz: str, total_tokens: int) -> None:
        with self._lock:
            self._inflight_tokens[clazz] = max(
                0, self._inflight_tokens.get(clazz, 0) - total_tokens)

    def note_shed(self, reason: str) -> None:
        telemetry.inc("ray_tpu_llm_shed_total", tags={"reason": reason})

    def _set_depth_gauge(self, clazz: str) -> None:
        with self._lock:
            depth = self._queued.get(clazz, 0)
        telemetry.set_gauge("ray_tpu_llm_admission_queue_depth", depth,
                            tags={"class": clazz})

    def queue_depth(self) -> int:
        with self._lock:
            return sum(self._queued.values())


@dataclass
class _Pending:
    pub_id: int
    prompt: List[int]
    params: SamplingParams
    clazz: str
    total_tokens: int
    t_submit: float
    deadline: float
    #: After this (caller timeout + grace) an uncollected request counts
    #: as abandoned and is reclaimed by the drive loop's sweep.
    abandon_deadline: float = 0.0
    #: Token budget released exactly once (a caller-timeout _abandon can
    #: race the engine finishing the same request).
    released: bool = False
    #: W3C trace linkage (util/tracing): the submitter's context and the
    #: request's own root span context.  Pipeline stages complete on the
    #: dispatcher/driver threads, so the contexts ride the request
    #: instead of thread-locals — queue-wait / prefill / KV-transfer /
    #: decode-admission spans all land in ONE trace tree.
    trace_parent: Any = None
    trace_root: Any = None
    t_submit_wall: float = 0.0


class DisaggServer:
    """Admission router + (optionally disaggregated) engines, one plane.

    Two background threads (both ``sanitizer.spawn``-registered and
    joined by :meth:`close`): the DISPATCHER moves admitted requests
    from the bounded router queue into the engine — running prefill and
    the KV handoff in disagg mode — and the DRIVER steps the decode
    engine and publishes finished results.
    """

    def __init__(self, build_params, *, mode: str = "disagg",
                 admission: Optional[AdmissionConfig] = None,
                 engine_options: Optional[Dict[str, Any]] = None,
                 store=None, record_token_times: bool = False,
                 poll_interval_s: float = 0.002):
        if mode not in ("disagg", "chunked", "inline"):
            raise ValueError(f"unknown mode {mode!r}")
        params, cfg = build_params() if callable(build_params) \
            else build_params
        eo = dict(engine_options or {})
        buckets = eo.get("prefill_buckets", (64, 256, 1024))
        if mode == "chunked":
            eo.setdefault("prefill_chunk", 64)
        else:
            eo.pop("prefill_chunk", None)
        self.mode = mode
        self.engine = InferenceEngine(
            params, cfg, record_token_times=record_token_times, **eo)
        self.prefill_worker = PrefillWorker(
            params, cfg, prefill_buckets=buckets,
            page_size=eo.get("page_size", 16)) \
            if mode == "disagg" else None
        self.admission = AdmissionController(admission or AdmissionConfig())
        self._store = store
        self._lock = threading.Lock()
        self._queue: "deque[_Pending]" = deque()
        self._events: Dict[int, threading.Event] = {}
        self._results: Dict[int, Dict[str, Any]] = {}
        self._meta: Dict[int, _Pending] = {}
        self._rid_to_pub: Dict[int, int] = {}
        self._pub_to_rid: Dict[int, int] = {}
        self._pub_ids = itertools.count(1)
        self._stop = threading.Event()
        self._work = threading.Event()
        self._poll = poll_interval_s
        self._last_sweep = 0.0
        self._dispatcher = sanitizer.spawn(
            self._dispatch_loop, name="disagg-dispatch")
        self._driver = sanitizer.spawn(
            self._drive_loop, name="disagg-drive")

    # -- intake -------------------------------------------------------------

    def submit(self, body: Dict[str, Any],
               clazz: Optional[str] = None) -> int:
        """Admit (or shed) one request; returns a result id to pass to
        :meth:`result`.  Sheds raise :class:`OverloadError` — the
        caller learns about overload in microseconds, not at its
        timeout."""
        if self._stop.is_set():
            raise RuntimeError("DisaggServer is closed")
        clazz = clazz or str(body.get("class", "default"))
        prompt = list(body["prompt_tokens"])
        params = SamplingParams.from_body(body)
        if self.prefill_worker is not None \
                and len(prompt) > self.prefill_worker.prefill_buckets[-1]:
            # Disagg prefill is bucketed; reject clearly at admission
            # instead of charging budget and failing at dispatch (the
            # chunked/inline modes serve any length via the chunked
            # program).
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the largest "
                f"disagg prefill bucket "
                f"({self.prefill_worker.prefill_buckets[-1]})")
        total = len(prompt) + params.max_tokens
        if clazz not in self.admission.cfg.classes:
            # Unknown class names coalesce onto "default" BEFORE any
            # counter is keyed: caller-supplied strings must not mint
            # per-name queue counters (that would void every queue
            # bound) or unbounded gauge tag cardinality.
            clazz = "default"
        reason = self.admission.try_admit(
            clazz, total, self.engine.load_stats())
        if reason is not None:
            self.admission.note_shed(reason)
            raise OverloadError(
                f"request shed ({reason}); retry with backoff")
        rc = self.admission.cfg.class_for(clazz)
        now = time.perf_counter()
        item = _Pending(next(self._pub_ids), prompt, params, clazz,
                        total, now, now + rc.queue_deadline_s,
                        abandon_deadline=now
                        + float(body.get("timeout_s", 300)) + 10.0)
        # Trace linkage: inherit the submitter's context (e.g. the serve
        # replica's execute span) so the LLM request renders as one tree.
        item.trace_parent = tracing.current()
        item.trace_root = tracing.new_child(item.trace_parent)
        item.t_submit_wall = time.time()
        ev = threading.Event()
        with self._lock:
            self._events[item.pub_id] = ev
            self._meta[item.pub_id] = item
            self._queue.append(item)
        self._work.set()
        return item.pub_id

    def result(self, pub_id: int, timeout_s: float = 300.0
               ) -> Dict[str, Any]:
        """Block for one submitted request's result.  On timeout the
        request is cancelled and its engine slot/pages freed (no
        abandoned-entry leak)."""
        now = time.perf_counter()
        with self._lock:
            ev = self._events.get(pub_id)
            item = self._meta.get(pub_id)
            if item is not None:
                # An actively-waiting caller extends the abandon window:
                # the sweep must never cancel work someone is blocked on
                # (result timeouts can exceed the submit-time default).
                item.abandon_deadline = max(item.abandon_deadline,
                                            now + timeout_s + 10.0)
        if ev is None:
            raise KeyError(f"unknown or already-collected id {pub_id}")
        if not ev.wait(timeout_s):
            self._abandon(pub_id)
            return {"error": "generation timed out",
                    "finish_reason": "timeout"}
        with self._lock:
            res = self._results.pop(pub_id, None)
            self._events.pop(pub_id, None)
            self._meta.pop(pub_id, None)
            self._pub_to_rid.pop(pub_id, None)
        if res is None:    # reclaimed between wake and collect
            return {"error": "request was cancelled",
                    "finish_reason": "cancelled"}
        return res

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Serve-replica entry point: submit + wait."""
        pub_id = self.submit(body)
        return self.result(pub_id,
                           timeout_s=float(body.get("timeout_s", 300)))

    def _trace_phase(self, item: _Pending, name: str, start_wall: float,
                     attrs: Optional[Dict[str, Any]] = None) -> None:
        """Record one pipeline-phase span under the request's root (a
        no-op when the request carries no trace context)."""
        if item.trace_root is None:
            return
        tracing.record_span(item.trace_root, name, start_wall,
                            time.time(), attrs or {})

    def _release_budget(self, item: Optional[_Pending]) -> None:
        """Return the class token budget exactly once per request (a
        caller-timeout abandon can race the engine finish)."""
        if item is None:
            return
        with self._lock:
            if item.released:
                return
            item.released = True
        self.admission.note_finished(item.clazz, item.total_tokens)

    def _abandon(self, pub_id: int) -> None:
        with self._lock:
            ev = self._events.pop(pub_id, None)
            self._results.pop(pub_id, None)
            item = self._meta.pop(pub_id, None)
            rid = self._pub_to_rid.pop(pub_id, None)
            if rid is not None:
                self._rid_to_pub.pop(rid, None)
            try:
                self._queue.remove(item)
                queued = True
            except ValueError:
                queued = False
        if item is not None:
            if queued:
                self.admission.note_dequeued(item.clazz)
            self._release_budget(item)
        if rid is not None:
            self.engine.cancel(rid)
        if ev is not None:
            # Wake any caller still blocked in result(): it reports
            # "cancelled" immediately instead of sleeping out its
            # timeout against an event nobody will ever set.
            ev.set()

    def _sweep_abandoned(self) -> None:
        """Reclaim requests whose caller stopped waiting (never called
        result()): frees the engine slot/pages and every bookkeeping
        entry — the same guarantee LLMServer's sweep gives.  Throttled:
        deadlines have 10 s granularity, so an O(pending) scan per
        decode step would be pure hot-loop overhead."""
        now = time.perf_counter()
        if now - self._last_sweep < 0.5:
            return
        self._last_sweep = now
        with self._lock:
            stale = [pub_id for pub_id, item in self._meta.items()
                     if now > item.abandon_deadline]
        for pub_id in stale:
            self._abandon(pub_id)

    # -- dispatch (router queue -> engine) ----------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            item = None
            with self._lock:
                if self._queue:
                    item = self._queue.popleft()
            if item is None:
                self._work.wait(0.02)
                self._work.clear()
                continue
            self._trace_phase(item, "queue_wait", item.t_submit_wall,
                              {"class": item.clazz})
            now = time.perf_counter()
            self.admission.note_queue_wait(now - item.t_submit)
            if now > item.deadline:
                self._finish_shed(item, "deadline")
                continue
            try:
                if self.mode == "disagg":
                    self._dispatch_disagg(item)
                else:
                    self._dispatch_engine(item)
            except Exception as e:  # publish, never wedge the loop
                self.admission.note_dequeued(item.clazz)
                self._release_budget(item)
                self._publish(item.pub_id,
                              {"error": str(e), "finish_reason": "error"})

    def _engine_has_room(self) -> bool:
        stats = self.engine.load_stats()
        return stats["waiting"] < max(2, self.engine.max_slots)

    def _gone(self, item: _Pending) -> bool:
        """True when the request was abandoned while the dispatcher held
        it (its _meta entry is gone): dispatch must drop it instead of
        handing a dead caller's request to the engine."""
        with self._lock:
            return item.pub_id not in self._meta

    def _map_or_cancel(self, item: _Pending, rid: int) -> None:
        """Register the engine rid for a dispatched item — unless the
        caller abandoned it during the hand-off, in which case the
        engine request is cancelled immediately (a dead request must
        not hold a decode slot to max_tokens under saturation)."""
        with self._lock:
            alive = item.pub_id in self._meta
            if alive:
                self._rid_to_pub[rid] = item.pub_id
                self._pub_to_rid[item.pub_id] = rid
        if not alive:
            self.engine.cancel(rid)
        self.admission.note_dequeued(item.clazz)
        self._work.set()

    def _dispatch_engine(self, item: _Pending) -> None:
        """Single-engine modes: hand to the engine once its own waiting
        list has room — until then the request stays the ROUTER's,
        where deadline shedding applies."""
        t_adm = time.time()
        while not self._stop.is_set():
            if self._gone(item):
                self.admission.note_dequeued(item.clazz)
                return
            if time.perf_counter() > item.deadline:
                self._finish_shed(item, "deadline")
                return
            if self._engine_has_room():
                break
            time.sleep(self._poll)
        if self._stop.is_set():
            self._finish_shed(item, "deadline")
            return
        rid = self.engine.add_request(item.prompt, item.params)
        self._trace_phase(item, "decode_admission", t_adm,
                          {"engine_rid": rid})
        self._map_or_cancel(item, rid)

    def _dispatch_disagg(self, item: _Pending) -> None:
        """Disagg mode: prefill on the prefill tier, hand KV pages to
        the decode engine through the shm object store (zero-copy on
        the same host), retry import under decode backpressure."""
        t_pf = time.time()
        handoff = self.prefill_worker.prefill(
            item.prompt, item.params, t_submit=item.t_submit)
        self._trace_phase(item, "prefill", t_pf,
                          {"prompt_tokens": len(item.prompt)})
        t_kv = time.time()
        oid = None
        keepalive = None
        if self._store is not None:
            from ..._private.ids import ObjectID
            oid = ObjectID.from_random()
            desc = export_handoff(self._store, oid, handoff)
            if desc is not None:
                handoff, keepalive = import_handoff(desc)
            else:
                oid = None  # store full: direct in-process handoff
        self._trace_phase(
            item, "kv_transfer", t_kv,
            {"transport": "shm_store" if oid is not None else "inline",
             "pages": getattr(handoff, "num_pages", None)})
        t_adm = time.time()
        rid = None
        gone = False
        while not self._stop.is_set():
            gone = self._gone(item)
            if gone:
                break
            rid = self.engine.import_prefill(handoff)
            if rid is not None:
                break
            if time.perf_counter() > item.deadline:
                break
            time.sleep(self._poll)
        # import_prefill copied the pages device-ward, so the staged
        # blob (and its shm views) can go: drop the export-time pin and
        # delete in one step.
        del keepalive
        if oid is not None:
            from ..._private.object_store import release_page_blob
            release_page_blob(self._store, oid)
        if gone:
            self.admission.note_dequeued(item.clazz)
            return
        if rid is None:
            self._finish_shed(item, "deadline")
            return
        # Admission wait INTO the decode batch (import retries under KV
        # backpressure) — distinct from the transfer itself.
        self._trace_phase(item, "decode_admission", t_adm,
                          {"engine_rid": rid})
        self._map_or_cancel(item, rid)

    def _finish_shed(self, item: _Pending, reason: str) -> None:
        self.admission.note_dequeued(item.clazz)
        self._release_budget(item)
        self.admission.note_shed(reason)
        self._publish(item.pub_id,
                      {"error": f"request shed ({reason}); retry with "
                                "backoff",
                       "reason": reason, "retriable": True,
                       "finish_reason": "shed"})

    # -- decode drive -------------------------------------------------------

    def _drive_loop(self) -> None:
        while not self._stop.is_set():
            if not self.engine.has_work():
                self._work.wait(0.02)
                self._work.clear()
                self._sweep_abandoned()
                continue
            for req in self.engine.step():
                self._on_engine_finish(req)
            self._sweep_abandoned()

    def _on_engine_finish(self, req) -> None:
        with self._lock:
            pub_id = self._rid_to_pub.pop(req.request_id, None)
            item = self._meta.get(pub_id) if pub_id is not None else None
        if pub_id is None:
            return
        self._release_budget(item)
        itl = [b - a for a, b in zip(req.token_times,
                                     req.token_times[1:])]
        self._publish(pub_id, {
            "output_tokens": list(req.output_tokens),
            "finish_reason": req.finish_reason,
            "ttft_s": (req.t_first - req.t_submit)
            if req.t_first and req.t_submit else None,
            "itl_s": itl,
        })

    def _publish(self, pub_id: int, result: Dict[str, Any]) -> None:
        with self._lock:
            ev = self._events.get(pub_id)
            item = self._meta.get(pub_id)
            if ev is None:       # abandoned while in flight: drop
                self._meta.pop(pub_id, None)
                self._pub_to_rid.pop(pub_id, None)
                return
            self._results[pub_id] = result
        if item is not None and item.trace_root is not None:
            # Close the request's root span (the phases above are its
            # children) under the submitter's context.
            tracing.record_span(
                item.trace_parent, "llm_request", item.t_submit_wall,
                time.time(),
                {"mode": self.mode, "class": item.clazz,
                 "finish_reason": result.get("finish_reason")},
                ctx=item.trace_root)
        ev.set()

    # -- introspection / lifecycle ------------------------------------------

    def load(self) -> Dict[str, Any]:
        stats = self.engine.load_stats()
        stats["router_queue"] = self.admission.queue_depth()
        stats["mode"] = self.mode
        return stats

    def close(self, timeout_s: float = 5.0) -> None:
        """Bounded shutdown: stop both loops, join them, and fail every
        still-pending request loudly (callers never hang on a closed
        server)."""
        self._stop.set()
        self._work.set()
        self._dispatcher.join(timeout_s)
        self._driver.join(timeout_s)
        with self._lock:
            for pub_id, ev in list(self._events.items()):
                if pub_id not in self._results:
                    self._results[pub_id] = {"error": "server closed",
                                             "finish_reason": "closed"}
                ev.set()

    # Serve teardown calls shutdown() on replicas that expose it.
    shutdown = close


def build_disagg_deployment(build_params, *, name: str = "llm_disagg",
                            mode: str = "disagg",
                            num_replicas: int = 1, num_tpus: int = 0,
                            max_ongoing_requests: int = 64,
                            max_queued_requests: Optional[int] = None,
                            admission: Optional[AdmissionConfig] = None,
                            engine_options: Optional[Dict[str, Any]] = None,
                            autoscaling_config=None):
    """The disagg plane as a serve deployment: each replica hosts one
    DisaggServer (prefill worker + decode engine + SLO router), and the
    serve handle path adds its own ``max_queued_requests`` admission
    bound in front."""
    from ... import serve

    dep = serve.deployment(
        DisaggServer, name=name, num_replicas=num_replicas,
        num_tpus=num_tpus, max_ongoing_requests=max_ongoing_requests,
        max_queued_requests=max_queued_requests,
        autoscaling_config=autoscaling_config)
    return dep.bind(build_params, mode=mode, admission=admission,
                    engine_options=engine_options)
