"""Versioned config push: the long-poll host/client pattern.

Reference: python/ray/serve/_private/long_poll.py (LongPollHost:318 —
routers block on a snapshot version and wake when the controller publishes
a change; config flows push-style, never per-request polling).  Here the
broker is in-process; routers and the HTTP ingress read cached snapshots
and block in ``wait_for_change`` only when they want push semantics.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple


class LongPollBroker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._snapshots: Dict[str, Any] = {}

    def publish(self, key: str, snapshot: Any) -> int:
        with self._cond:
            v = self._versions.get(key, 0) + 1
            self._versions[key] = v
            self._snapshots[key] = snapshot
            self._cond.notify_all()
            return v

    def get(self, key: str) -> Tuple[int, Any]:
        with self._lock:
            return self._versions.get(key, 0), self._snapshots.get(key)

    def wait_for_change(self, key: str, seen_version: int,
                        timeout: Optional[float] = None
                        ) -> Tuple[int, Any]:
        """Block until the key's version exceeds ``seen_version``; returns
        (version, snapshot) — possibly the unchanged pair on timeout."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._versions.get(key, 0) <= seen_version:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._versions.get(key, 0), self._snapshots.get(key)
