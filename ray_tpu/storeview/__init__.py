"""ray_tpu.storeview: object-store lifecycle tracing + memory telescope.

The data-plane counterpart of ``schedview``: where the scheduler ring
answers "why is this task pending", this package answers "where is this
object, who pins it, why is it spilled, what did localizing it cost".

* ``StoreEventRing`` — bounded, mono-stamped ring of object lifecycle
  events (create→seal→pin/unpin→push/pull→spill→restore→delete), one per
  store instance, folded lazily into a per-object latest-state index.
  Reference analog: Ray reconstructs object state from plasma metadata +
  the reference counter for ``ray memory``
  (src/ray/object_manager/pull_manager.h:50); nothing keeps the history.
* ``explain`` / ``leak_candidates`` / ``top_pinned`` — the point lookups
  behind ``ray-tpu obj why``, ``ray-tpu memory`` leak detection, and the
  enriched ``ObjectStoreFullError`` message.
* ``RAY_TPU_STORE_TRACE=0`` kills recording (same switch idiom as
  ``RAY_TPU_SCHED_TRACE``); the dataplane bench's off/on overhead reps
  toggle ``set_enabled``.

Series published from the ring + ``store.stats()`` live in the ``store``
telemetry subsystem (see README "Data-plane introspection").
"""

from ray_tpu.storeview.events import (  # noqa: F401
    EVENT_KINDS,
    E_CREATE,
    E_DELETE,
    E_EVICT,
    E_GET,
    E_PIN,
    E_PULL,
    E_PUSH,
    E_RESTORE,
    E_SEAL,
    E_SPILL,
    E_UNPIN,
    StoreEventRing,
    enabled,
    set_enabled,
)

__all__ = [
    "StoreEventRing",
    "enabled",
    "set_enabled",
    "EVENT_KINDS",
    "E_CREATE", "E_SEAL", "E_GET", "E_PIN", "E_UNPIN", "E_PUSH",
    "E_PULL", "E_SPILL", "E_RESTORE", "E_EVICT", "E_DELETE",
]
