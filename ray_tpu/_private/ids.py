"""Structured binary identifiers for the ray_tpu runtime.

Mirrors the reference's lineage-embedding ID scheme (reference:
src/ray/common/id.h — BaseID:53, JobID:103, ActorID:124 contains JobID,
TaskID:159 contains ActorID, ObjectID:231 contains TaskID + index,
PlacementGroupID:300).  Embedding parent IDs means ownership and lineage can
be recovered from an ID alone without a directory lookup — e.g. any ObjectID
names the task that produced it, and any TaskID names the actor/job it ran
under.  This is load-bearing for lineage reconstruction and for routing.

Layout (bytes):
    JobID            : 4   random
    NodeID           : 16  random
    WorkerID         : 16  random
    ActorID          : 4(job) + 8 random                      = 12
    TaskID           : 12(actor) + 6 random                   = 18
    ObjectID         : 18(task) + 4 LE index                  = 22
    PlacementGroupID : 4(job) + 10 random                     = 14
"""

from __future__ import annotations

import os
import struct
import threading

_NIL_FILL = b"\xff"


class BaseID:
    SIZE = 0
    __slots__ = ("_bytes", "_hash", "_hex")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}")
        self._bytes = bytes(binary)
        self._hash = None
        self._hex = None

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(_NIL_FILL * cls.SIZE)

    @classmethod
    def from_hex(cls, hexstr: str) -> "BaseID":
        return cls(bytes.fromhex(hexstr))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        h = self._hex
        if h is None:
            h = self._hex = self._bytes.hex()
        return h

    def is_nil(self) -> bool:
        return self._bytes == _NIL_FILL * self.SIZE

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        # Cached: IDs key every hot dict (object directory, running tasks)
        # and re-hashing 20+ bytes per lookup showed up in dispatch profiles.
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4
    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack("<I", value))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = JobID.SIZE + 8

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(8))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class _RandPool:
    """Buffered os.urandom: one syscall per ~680 ids instead of one per id
    (TaskID.of is on the per-call submit path)."""

    __slots__ = ("_buf", "_pos", "_lock")

    def __init__(self):
        self._buf = b""
        self._pos = 1 << 30
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            pos = self._pos
            if pos + n > len(self._buf):
                self._buf = os.urandom(4096)
                pos = 0
            self._pos = pos + n
            return self._buf[pos:pos + n]


_rand_pool = _RandPool()


class TaskID(BaseID):
    SIZE = ActorID.SIZE + 6

    @classmethod
    def of(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + _rand_pool.take(6))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        """The synthetic root task a driver's objects are owned by."""
        return cls(job_id.binary() + b"\x00" * 8 + b"\x00" * 6)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[: ActorID.SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class ObjectID(BaseID):
    SIZE = TaskID.SIZE + 4

    @classmethod
    def of(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[TaskID.SIZE:])[0]


class PlacementGroupID(BaseID):
    SIZE = JobID.SIZE + 10

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(job_id.binary() + os.urandom(10))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])
