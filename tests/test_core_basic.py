"""Core substrate unit tests: ids, config, serialization, object store."""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.config import Config
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID)
from ray_tpu._private.object_store import SharedMemoryStore
from ray_tpu._private.resources import ResourceSet, task_resources


class TestIDs:
    def test_sizes_and_lineage(self):
        job = JobID.from_int(7)
        actor = ActorID.of(job)
        task = TaskID.of(actor)
        obj = ObjectID.of(task, 3)
        assert obj.task_id() == task
        assert task.actor_id() == actor
        assert actor.job_id() == job
        assert obj.job_id() == job
        assert obj.index() == 3

    def test_hex_roundtrip(self):
        nid = NodeID.from_random()
        assert NodeID.from_hex(nid.hex()) == nid

    def test_nil(self):
        assert ObjectID.nil().is_nil()
        assert not ObjectID.of(TaskID.for_driver(JobID.from_int(1)), 0).is_nil()

    def test_pg_id(self):
        job = JobID.from_int(9)
        pg = PlacementGroupID.of(job)
        assert pg.job_id() == job

    def test_hashable(self):
        job = JobID.from_int(1)
        t = TaskID.for_driver(job)
        s = {ObjectID.of(t, i) for i in range(10)}
        assert len(s) == 10


class TestConfig:
    def test_defaults(self):
        Config.initialize()
        assert Config.get("max_inline_object_size") == 100 * 1024

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_MAX_INLINE_OBJECT_SIZE", "1234")
        Config.initialize()
        assert Config.get("max_inline_object_size") == 1234
        monkeypatch.delenv("RAY_TPU_MAX_INLINE_OBJECT_SIZE")
        Config.initialize()

    def test_unknown_flag(self):
        with pytest.raises(KeyError):
            Config.get("no_such_flag")

    def test_blob_roundtrip(self, monkeypatch):
        Config.initialize()
        blob = Config.blob()
        monkeypatch.setenv("RAY_TPU_CONFIG_BLOB", blob)
        Config.initialize({})
        assert Config.get("max_inline_object_size") == 100 * 1024


class TestSerialization:
    def test_roundtrip_scalars(self):
        for v in [1, "x", None, {"a": [1, 2]}, (1, 2)]:
            assert serialization.unpack_payload(
                serialization.pack_payload(v)) == v

    def test_numpy_out_of_band(self):
        arr = np.arange(1000, dtype=np.float64)
        meta, bufs = serialization.serialize_payload(arr)
        assert sum(b.nbytes for b in bufs) >= arr.nbytes
        out = serialization.unpack_payload(serialization.pack_payload(arr))
        np.testing.assert_array_equal(out, arr)

    def test_closure(self):
        y = 10
        fn = serialization.loads_control(
            serialization.dumps_control(lambda x: x + y))
        assert fn(5) == 15


class TestResourceSet:
    def test_arithmetic(self):
        a = ResourceSet({"CPU": 4, "TPU": 8})
        b = ResourceSet({"CPU": 1, "TPU": 2})
        c = a - b
        assert c.get("CPU") == 3 and c.get("TPU") == 6
        assert (c + b).get("TPU") == 8

    def test_fits(self):
        avail = ResourceSet({"CPU": 2})
        assert ResourceSet({"CPU": 2}).fits(avail)
        assert not ResourceSet({"CPU": 2.5}).fits(avail)
        assert not ResourceSet({"TPU": 1}).fits(avail)
        assert ResourceSet({}).fits(avail)

    def test_negative_raises(self):
        with pytest.raises(ValueError):
            ResourceSet({"CPU": 1}) - ResourceSet({"CPU": 2})

    def test_task_resources_defaults(self):
        r = task_resources(None, None, None, None)
        assert r.get("CPU") == 1.0
        r = task_resources(2, 4, None, {"custom": 1})
        assert r.get("TPU") == 4 and r.get("custom") == 1


class TestObjectStore:
    def _oid(self, i=0):
        return ObjectID.of(TaskID.of(ActorID.of(JobID.from_int(99))), i)

    def test_put_get(self):
        store = SharedMemoryStore(capacity_bytes=10 << 20)
        oid = self._oid(1)
        arr = np.arange(10000, dtype=np.int64)
        store.put(oid, {"x": arr, "y": "hello"})
        out = store.get(oid)
        np.testing.assert_array_equal(out["x"], arr)
        assert out["y"] == "hello"
        store.shutdown()

    def test_spill_restore(self):
        store = SharedMemoryStore(capacity_bytes=1 << 20)
        arrs = {}
        for i in range(5):
            oid = self._oid(i)
            arrs[oid] = np.full(40000, i, dtype=np.int64)  # 320KB each
            store.put(oid, arrs[oid])
        assert store.num_spilled > 0
        for oid, arr in arrs.items():
            np.testing.assert_array_equal(store.get(oid), arr)
        assert store.num_restored > 0
        store.shutdown()

    def test_delete(self):
        store = SharedMemoryStore(capacity_bytes=1 << 20)
        oid = self._oid(7)
        store.put(oid, b"x" * 1000)
        assert store.contains(oid)
        store.delete(oid)
        assert not store.contains(oid)
        store.shutdown()

    def test_full_raises(self):
        from ray_tpu._private.object_store import ObjectStoreFullError
        store = SharedMemoryStore(capacity_bytes=1000)
        oid = self._oid(8)
        store.put(oid, b"a" * 100)
        store.pin(oid)
        with pytest.raises(ObjectStoreFullError):
            store.put(self._oid(9), b"b" * 2000)
        store.shutdown()
