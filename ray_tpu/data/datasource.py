"""File datasources: binary blobs, images, TFRecord.

Reference: python/ray/data/_internal/datasource/ (image_datasource.py,
binary_datasource.py, tfrecords_datasource.py).  The readers produce
dict-of-ndarray blocks on the existing read-marker path (loaders execute
inside read tasks, not on the driver).

TFRecord support is self-contained: the record framing (length + masked
crc32c) and the tf.train.Example protobuf (BytesList/FloatList/Int64List
features) are implemented directly — no tensorflow dependency — so shards
written here are readable by TF tooling and vice versa.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

# --------------------------------------------------------------------- #
# crc32c (Castagnoli), table-driven — used for TFRecord masked crcs.
# --------------------------------------------------------------------- #

_CRC_TABLE: Optional[List[int]] = None


def _crc32c_table() -> List[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# --------------------------------------------------------------------- #
# TFRecord framing
# --------------------------------------------------------------------- #

def tfrecord_iter(path: str, verify_crc: bool = False) -> Iterator[bytes]:
    """Yield raw record payloads from a TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (lcrc,) = struct.unpack("<I", header[8:])
                if _masked_crc(header[:8]) != lcrc:
                    raise ValueError(f"{path}: bad length crc")
            payload = f.read(length)
            footer = f.read(4)
            if len(payload) < length or len(footer) < 4:
                raise ValueError(f"{path}: truncated record")
            if verify_crc:
                (pcrc,) = struct.unpack("<I", footer)
                if _masked_crc(payload) != pcrc:
                    raise ValueError(f"{path}: bad payload crc")
            yield payload


def tfrecord_write(path: str, payloads: Iterator[bytes]) -> None:
    with open(path, "wb") as f:
        for p in payloads:
            header = struct.pack("<Q", len(p))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(p)
            f.write(struct.pack("<I", _masked_crc(p)))


# --------------------------------------------------------------------- #
# Minimal tf.train.Example protobuf codec
#   Example{1: Features}; Features{1: map<string, Feature>};
#   Feature{1: BytesList, 2: FloatList, 3: Int64List};
#   BytesList{1: repeated bytes}, FloatList{1: repeated float packed},
#   Int64List{1: repeated int64 packed varint}.
# --------------------------------------------------------------------- #

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _field(tag: int, wire: int, payload: bytes) -> bytes:
    return _varint((tag << 3) | wire) + (
        _varint(len(payload)) + payload if wire == 2 else payload)


def encode_example(features: Dict[str, Any]) -> bytes:
    """dict of str -> bytes | str | float(s) | int(s) -> tf.train.Example."""
    entries = b""
    for key, value in features.items():
        if isinstance(value, (bytes, str)):
            value = [value.encode() if isinstance(value, str) else value]
            inner = b"".join(_field(1, 2, v) for v in value)
            feature = _field(1, 2, inner)  # BytesList
        elif isinstance(value, (list, tuple, np.ndarray)) and len(value) \
                and isinstance(np.asarray(value).flat[0], (bytes, str)):
            vs = [v.encode() if isinstance(v, str) else v for v in value]
            feature = _field(1, 2, b"".join(_field(1, 2, v) for v in vs))
        else:
            arr = np.atleast_1d(np.asarray(value))
            if np.issubdtype(arr.dtype, np.floating):
                packed = struct.pack(f"<{arr.size}f",
                                     *arr.astype(np.float32).tolist())
                feature = _field(2, 2, _field(1, 2, packed))  # FloatList
            else:
                packed = b"".join(
                    _varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                    for v in arr.astype(np.int64).tolist())
                feature = _field(3, 2, _field(1, 2, packed))  # Int64List
        entry = _field(1, 2, key.encode()) + _field(2, 2, feature)
        entries += _field(1, 2, entry)  # map entry in Features
    return _field(1, 2, entries)  # Example.features


def _parse_fields(buf: bytes) -> Iterator[tuple]:
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        tag, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield tag, wire, val


def decode_example(payload: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes -> dict of str -> np.ndarray | list[bytes]."""
    out: Dict[str, Any] = {}
    features = b""
    for tag, _w, val in _parse_fields(payload):
        if tag == 1:
            features = val
    for tag, _w, entry in _parse_fields(features):
        if tag != 1:
            continue
        key = None
        feature = b""
        for t2, _w2, v2 in _parse_fields(entry):
            if t2 == 1:
                key = v2.decode()
            elif t2 == 2:
                feature = v2
        if key is None:
            continue
        for t3, _w3, v3 in _parse_fields(feature):
            if t3 == 1:  # BytesList
                vals = [v for t4, _w4, v in _parse_fields(v3) if t4 == 1]
                out[key] = vals
            elif t3 == 2:  # FloatList (packed)
                for t4, _w4, v in _parse_fields(v3):
                    if t4 == 1:
                        out[key] = np.frombuffer(v, np.float32).copy()
            elif t3 == 3:  # Int64List (packed varints)
                for t4, _w4, v in _parse_fields(v3):
                    if t4 == 1:
                        vals64: List[int] = []
                        pos = 0
                        while pos < len(v):
                            x, pos = _read_varint(v, pos)
                            if x >= 1 << 63:
                                x -= 1 << 64
                            vals64.append(x)
                        out[key] = np.asarray(vals64, np.int64)
    return out


# --------------------------------------------------------------------- #
# Block loaders (run inside read tasks via the read-marker path)
# --------------------------------------------------------------------- #

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def load_binary_block(path: str):
    with open(path, "rb") as f:
        data = f.read()
    return {"bytes": np.asarray([data], object),
            "path": np.asarray([path], object)}


def load_image_block(path: str, size=None, mode=None):
    """Decode one image file -> a single-row block.  ``size`` (H, W)
    resizes at decode; ``mode`` converts (e.g. 'RGB', 'L')."""
    from PIL import Image
    img = Image.open(path)
    if mode:
        img = img.convert(mode)
    if size is not None:
        img = img.resize((size[1], size[0]))
    arr = np.asarray(img)
    return {"image": arr[None, ...],
            "path": np.asarray([path], object)}


def load_tfrecord_block(path: str, verify_crc: bool = False):
    rows: Dict[str, List[Any]] = {}
    count = 0
    for payload in tfrecord_iter(path, verify_crc=verify_crc):
        ex = decode_example(payload)
        for k, v in ex.items():
            rows.setdefault(k, [])
            # Backfill rows missed before this key first appeared.
            while len(rows[k]) < count:
                rows[k].append(None)
            if isinstance(v, list) and len(v) == 1:
                v = v[0]
            rows[k].append(v)
        count += 1
    for k in rows:
        while len(rows[k]) < count:
            rows[k].append(None)
    out: Dict[str, np.ndarray] = {}
    for k, vs in rows.items():
        if vs and isinstance(vs[0], np.ndarray) and \
                all(isinstance(v, np.ndarray) and v.shape == vs[0].shape
                    for v in vs):
            stacked = np.stack(vs)
            # Scalar-per-row features flatten to a 1-D column.
            if stacked.ndim == 2 and stacked.shape[1] == 1:
                stacked = stacked[:, 0]
            out[k] = stacked
        else:
            out[k] = np.asarray(vs, object)
    return out


def write_tfrecord_block(block: Dict[str, np.ndarray], path: str) -> None:
    n = len(next(iter(block.values()))) if block else 0

    def payloads():
        for i in range(n):
            yield encode_example({k: v[i] for k, v in block.items()})
    tfrecord_write(path, payloads())


def expand_paths(paths, exts=None) -> List[str]:
    """Expand files / dirs / globs into a sorted file list."""
    import glob as g
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in files)
        elif any(ch in p for ch in "*?["):
            out.extend(g.glob(p))
        else:
            out.append(p)
    if exts:
        out = [p for p in out if p.lower().endswith(exts)]
    return sorted(out)
