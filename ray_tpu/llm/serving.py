"""LLM serving: the engine as a serve deployment.

Reference analog: serve.llm build_openai_app / VLLMService (reference:
python/ray/serve/llm, llm/_internal/serve/) — a replica owns the engine
(and its chips via ``num_tpus``), requests join the continuous batch, and
the serve layer provides routing/autoscaling/self-healing around it.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from .engine import InferenceEngine, SamplingParams


class LLMServer:
    """Deployment callable hosting one InferenceEngine.

    A background thread drives ``engine.step()`` whenever work exists;
    requests block on a per-request event (continuous batching means a
    request joins mid-flight instead of waiting for a batch boundary).
    """

    def __init__(self, build_params: Callable[[], tuple],
                 engine_options: Optional[Dict[str, Any]] = None):
        params, cfg = build_params()
        self.engine = InferenceEngine(params, cfg,
                                      **(engine_options or {}))
        self._results: Dict[int, Any] = {}
        self._events: Dict[int, threading.Event] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drive, daemon=True)
        self._thread.start()

    def _drive(self) -> None:
        import time
        while not self._stop.is_set():
            if not self.engine.has_work():
                time.sleep(0.005)
                continue
            for req in self.engine.step():
                with self._lock:
                    ev = self._events.get(req.request_id)
                    if ev is not None:
                        # Only store results someone is waiting for
                        # (abandoned requests would otherwise accumulate).
                        self._results[req.request_id] = req
                if ev is not None:
                    ev.set()

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """{"prompt_tokens": [...], "max_tokens": N, ...} ->
        {"output_tokens": [...], "finish_reason": ...}"""
        params = SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            stop_token_ids=tuple(body.get("stop_token_ids", ())))
        ev = threading.Event()
        with self._lock:
            rid = self.engine.add_request(
                list(body["prompt_tokens"]), params)
            self._events[rid] = ev
        if not ev.wait(timeout=float(body.get("timeout_s", 300))):
            # Abandon cleanly: release the engine slot/pages and drop the
            # bookkeeping so repeated timeouts can't leak.
            with self._lock:
                self._events.pop(rid, None)
                self._results.pop(rid, None)
            self.engine.cancel(rid)
            return {"error": "generation timed out"}
        with self._lock:
            req = self._results.pop(rid)
            self._events.pop(rid, None)
        return {"output_tokens": req.output_tokens,
                "finish_reason": req.finish_reason}

    def stream(self, body: Dict[str, Any]):
        """Token-streaming entry point: yields tokens as the engine emits
        them (served via ``handle.options(stream=True)`` -> a streaming
        actor call, so each token publishes the moment it exists —
        reference: serve.llm streaming chat completions)."""
        import time as _time
        params = SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            stop_token_ids=tuple(body.get("stop_token_ids", ())))
        ev = threading.Event()
        with self._lock:
            rid = self.engine.add_request(
                list(body["prompt_tokens"]), params)
            self._events[rid] = ev
            req = self.engine.running.get(rid)
        deadline = _time.monotonic() + float(body.get("timeout_s", 300))
        sent = 0
        try:
            while True:
                done = ev.wait(timeout=0.01)
                toks = list(req.output_tokens) if req is not None else []
                while sent < len(toks):
                    yield {"token": int(toks[sent]), "index": sent}
                    sent += 1
                if done and sent >= len(req.output_tokens):
                    yield {"finish_reason": req.finish_reason,
                           "num_tokens": sent}
                    return
                if _time.monotonic() > deadline:
                    self.engine.cancel(rid)
                    yield {"error": "generation timed out"}
                    return
        finally:
            with self._lock:
                self._events.pop(rid, None)
                self._results.pop(rid, None)

    def generate_batch(self, prompts: List[List[int]],
                       max_tokens: int = 64) -> List[List[int]]:
        """Offline batch entry point (reference: llm batch stages)."""
        evs = []
        with self._lock:
            for p in prompts:
                rid = self.engine.add_request(
                    list(p), SamplingParams(max_tokens=max_tokens))
                ev = threading.Event()
                self._events[rid] = ev
                evs.append((rid, ev))
        out = []
        for rid, ev in evs:
            ev.wait(timeout=600)
            with self._lock:
                req = self._results.pop(rid, None)
                self._events.pop(rid, None)
            out.append(req.output_tokens if req else [])
        return out

    def shutdown(self) -> None:
        self._stop.set()


def build_llm_deployment(build_params: Callable[[], tuple], *,
                         name: str = "llm",
                         num_replicas: int = 1,
                         num_tpus: int = 0,
                         max_ongoing_requests: int = 64,
                         engine_options: Optional[Dict[str, Any]] = None,
                         autoscaling_config=None):
    """Wrap the engine in a serve deployment (reference:
    serve/llm build_llm_deployment)."""
    from .. import serve

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        num_tpus=num_tpus, max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=autoscaling_config)
    return dep.bind(build_params, engine_options)
