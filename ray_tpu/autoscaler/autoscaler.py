"""The reconciler: demand -> desired node set -> provider actions.

Reference: v2 Autoscaler (autoscaler.py:51) update loop — read demand,
run the ResourceDemandScheduler bin-packing (v2/scheduler.py:822), diff
against the instance manager's view, launch/terminate.  Simplifications
kept honest: first-fit-decreasing bin-packing over configured node types,
idle-timeout downscaling (a node with no running work past the timeout),
min/max clamps per type.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .providers import NodeProvider


@dataclass
class NodeTypeConfig:
    """reference: available_node_types entries in the autoscaler yaml."""
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0


class Autoscaler:
    """Reconciles cluster size against scheduler demand."""

    def __init__(self, runtime, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.runtime = runtime
        self.provider = provider
        self.config = config
        # provider_id -> (node_type, launch_ts)
        self._launched: Dict[str, tuple] = {}
        # node_id (runtime) -> first-seen-idle timestamp
        self._idle_since: Dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- loop ---------------------------------------------------------------

    def _loop(self) -> None:
        # Satisfy min_workers immediately.
        for name, ntc in self.config.node_types.items():
            for _ in range(ntc.min_workers):
                self._launch(name, ntc)
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self._reconcile()
            except Exception:
                import traceback
                traceback.print_exc()

    def _count_by_type(self) -> Dict[str, int]:
        live = set(self.provider.non_terminated_nodes())
        counts: Dict[str, int] = {}
        for pid, (ntype, _ts) in list(self._launched.items()):
            if pid in live:
                counts[ntype] = counts.get(ntype, 0) + 1
            else:
                self._launched.pop(pid, None)
        return counts

    def _launch(self, name: str, ntc: NodeTypeConfig) -> None:
        pid = self.provider.create_node(name, ntc.resources)
        self._launched[pid] = (name, time.monotonic())

    def _reconcile(self) -> None:
        demand = self.runtime.scheduler.pending_demand()
        counts = self._count_by_type()

        # -- upscale: first-fit-decreasing bin-pack of unmet demand onto
        # node types (reference: v2/scheduler.py bin-packing). Capacity
        # already free in the cluster absorbs demand first (aggregate
        # pool approximation; per-node packing is the scheduler's job).
        pool = dict(self.runtime.ctl_available_resources())

        def fits_pool(shape: Dict[str, float]) -> bool:
            return all(pool.get(k, 0.0) >= v for k, v in shape.items())

        unmet: List[Dict[str, float]] = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            if fits_pool(shape):
                for k, v in shape.items():
                    pool[k] = pool.get(k, 0.0) - v
            else:
                unmet.append(shape)

        to_launch: Dict[str, int] = {}
        virtual: List[Dict[str, float]] = []
        for shape in unmet:
            placed = False
            for v in virtual:
                if all(v.get(k, 0.0) >= amt for k, amt in shape.items()):
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    placed = True
                    break
            if placed:
                continue
            for name, ntc in self.config.node_types.items():
                have = counts.get(name, 0) + to_launch.get(name, 0)
                if have >= ntc.max_workers:
                    continue
                if all(ntc.resources.get(k, 0.0) >= amt
                       for k, amt in shape.items()):
                    to_launch[name] = to_launch.get(name, 0) + 1
                    v = dict(ntc.resources)
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    virtual.append(v)
                    placed = True
                    break
            # Unplaceable on any type: stays pending (surfaced by status).
        for name, n in to_launch.items():
            for _ in range(n):
                self._launch(name, self.config.node_types[name])

        # -- downscale: terminate nodes idle past the timeout, respecting
        # per-type minimums (reference: idle node termination in v1/v2).
        if not demand:
            self._downscale_idle(counts)

    def _downscale_idle(self, counts: Dict[str, int]) -> None:
        rt = self.runtime
        now = time.monotonic()
        busy_nodes = set()
        with rt._running_lock:
            for t in rt._running.values():
                busy_nodes.add(t.node_id)
        with rt._actors_lock:
            for ast in rt._actors.values():
                if ast.node_id is not None:
                    busy_nodes.add(ast.node_id)

        # Match provider nodes to runtime nodes by recency of launch: the
        # provider only knows pids; the runtime only knows node ids.  Idle
        # detection operates on runtime node ids; termination picks the
        # youngest idle provider node of a type over its minimum.
        alive = [n for n in rt.controller.alive_nodes() if not n.is_head]
        idle_os_pids = set()
        for n in alive:
            if n.node_id in busy_nodes:
                self._idle_since.pop(n.node_id, None)
                continue
            first = self._idle_since.setdefault(n.node_id, now)
            if now - first >= self.config.idle_timeout_s:
                try:
                    idle_os_pids.add(int(n.labels.get("os_pid", 0)))
                except (TypeError, ValueError):
                    pass
        idle_os_pids.discard(0)
        if not idle_os_pids:
            return
        # Terminate exactly the IDLE provider nodes (matched by the OS pid
        # each node reported at registration), respecting type minimums.
        get_pid = getattr(self.provider, "node_os_pid", None)
        remaining = dict(counts)
        for pid, (ntype, _ts) in list(self._launched.items()):
            if remaining.get(ntype, 0) <=                     self.config.node_types[ntype].min_workers:
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None and os_pid in idle_os_pids:
                self.provider.terminate_node(pid)
                self._launched.pop(pid, None)
                remaining[ntype] = remaining.get(ntype, 0) - 1

    # -- introspection ------------------------------------------------------

    def status(self) -> Dict:
        return {
            "nodes_by_type": self._count_by_type(),
            "pending_demand": len(self.runtime.scheduler.pending_demand()),
        }
