"""Cluster pubsub tests (reference analog: src/ray/pubsub tests — buffered
long-poll delivery)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import pubsub


class TestPubsub:
    def test_publish_poll_roundtrip(self, ray_start):
        pubsub.publish("t1", {"n": 1})
        pubsub.publish("t1", {"n": 2})
        seq, msgs = pubsub.poll("t1", after_seq=0, timeout=5)
        assert [m["n"] for m in msgs] == [1, 2]
        # Nothing newer yet: times out without busy-waiting.
        seq2, more = pubsub.poll("t1", after_seq=seq, timeout=0.1)
        assert more == []
        pubsub.publish("t1", {"n": 3})
        _, more = pubsub.poll("t1", after_seq=seq, timeout=5)
        assert [m["n"] for m in more] == [3]

    def test_long_poll_wakes_on_publish(self, ray_start):
        got = {}

        def waiter():
            t0 = time.monotonic()
            seq, msgs = pubsub.poll("t2", after_seq=0, timeout=10)
            got["latency"] = time.monotonic() - t0
            got["msgs"] = msgs

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        pubsub.publish("t2", "wake")
        t.join(timeout=5)
        assert got["msgs"] == ["wake"]
        assert got["latency"] < 2.0  # woke on publish, not timeout

    def test_workers_publish_and_subscribe(self, ray_start):
        """Cross-process: a worker publishes, the driver receives — and
        vice versa (reference: worker pubsub through GCS)."""

        @ray_tpu.remote
        def announce(i):
            from ray_tpu.util import pubsub as ps
            ps.publish("t3", f"from-worker-{i}")
            return 1

        ray_tpu.get([announce.remote(i) for i in range(3)])
        _, msgs = pubsub.poll("t3", after_seq=0, timeout=5)
        assert sorted(msgs) == [f"from-worker-{i}" for i in range(3)]

        pubsub.publish("t4", "driver-says-hi")

        @ray_tpu.remote
        def receive():
            from ray_tpu.util import pubsub as ps
            _, m = ps.poll("t4", after_seq=0, timeout=10)
            return m

        assert ray_tpu.get(receive.remote(), timeout=30) == ["driver-says-hi"]

    def test_listen_from_now_skips_history(self, ray_start):
        pubsub.publish("t5", "old")
        out = []

        def consume():
            for m in pubsub.listen("t5", poll_timeout=1.0):
                out.append(m)
                return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        pubsub.publish("t5", "new")
        t.join(timeout=10)
        assert out == ["new"]

    def test_ring_bounded(self, ray_start):
        rt = ray_start
        for i in range(1200):
            rt.controller.publish("t6", i)
        _, msgs = rt.controller.pubsub_poll("t6", after_seq=0, timeout=0)
        assert len(msgs) == 1000  # oldest 200 overwritten
        assert msgs[0] == 200 and msgs[-1] == 1199
