"""ray_tpu.train — SPMD training orchestration (Ray Train v2 equivalent)."""

from ._checkpoint import (Checkpoint, CheckpointManager, load_pytree,
                          save_pytree)
from ._context import (TrainContext, get_context, get_mesh, load_checkpoint,
                       load_sharded, report, save_checkpoint, shard,
                       shard_batch)
from .controller import CrashLoopError
from .mesh.config import MeshConfig
from .trainer import (CheckpointConfig, FailureConfig, JaxTrainer, Result,
                      RunConfig, ScalingConfig)
from .watchdog import TrainWatchdog, WatchdogConfig
# Step-phase attribution (ray_tpu.profiler): declare what each slice of
# a step was — train.step_phase("data_wait") / train.fence(arrays) —
# and report() decomposes every step into
# ray_tpu_train_step_phase_seconds{phase}.
from ..profiler.attribution import fence, step_phase

__all__ = [
    "JaxTrainer", "ScalingConfig", "RunConfig", "FailureConfig",
    "CheckpointConfig", "Result", "Checkpoint", "CheckpointManager",
    "get_context", "report", "TrainContext", "save_pytree", "load_pytree",
    "save_checkpoint", "load_checkpoint", "CrashLoopError",
    "WatchdogConfig", "TrainWatchdog", "step_phase", "fence",
    "MeshConfig", "get_mesh", "shard", "shard_batch", "load_sharded",
]
