"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/ — ReplayBuffer (uniform ring
buffer) and PrioritizedEpisodeReplayBuffer (proportional prioritization,
Schaul et al. 2015).  Stored column-wise in preallocated numpy arrays so
``sample`` is a single fancy-index — the throughput-relevant layout for
feeding jit'd update steps.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, **transition: np.ndarray) -> None:
        """Add a batch of transitions (first axis = batch)."""
        n = len(next(iter(transition.values())))
        if not self._cols:
            for k, v in transition.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity, *v.shape[1:]),
                                         v.dtype)
        for k, v in transition.items():
            v = np.asarray(v)
            idx = (self._next + np.arange(n)) % self.capacity
            self._cols[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: c[idx] for k, c in self._cols.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay with importance weights."""

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, **transition: np.ndarray) -> None:
        n = len(next(iter(transition.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(**transition)
        self._prio[idx] = self._max_prio

    def sample(self, batch_size: int
               ) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        """Returns (batch, indices, importance_weights)."""
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        p = self._prio[:self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, batch_size, p=p)
        weights = (self._size * p[idx]) ** (-self.beta)
        weights = weights / weights.max()
        batch = {k: c[idx] for k, c in self._cols.items()}
        return batch, idx, weights.astype(np.float32)

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prio = np.abs(td_errors) + 1e-6
        self._prio[idx] = prio
        self._max_prio = max(self._max_prio, float(prio.max()))
