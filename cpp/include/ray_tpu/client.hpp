// ray_tpu C++ user API: task and actor calls from native code.
//
// Reference analog: cpp/src/ray/api.cc (ray::Task / ray::Actor over the
// core-worker ABI).  This client speaks the gateway protocol of
// ray_tpu/cpp_gateway.py — 4-byte little-endian length-prefixed JSON
// frames over TCP, token handshake first — and exposes:
//
//   ray_tpu::Client c(host, port, token);
//   std::string ref = c.submit("add", "[2, 40]");        // args as JSON
//   ray_tpu::Result r = c.get(ref);                      // r.result JSON
//   std::string ref2 = c.call_actor("counter", "", "bump", "[1]");
//
// Tensor results arrive as a typed shm segment (r.tensor_segment) mapped
// zero-copy with tensor_reader below (layout: tensor_writer.hpp).
// Argument/result payloads are JSON strings: the client does NOT bundle a
// general JSON library; the envelope fields it needs are extracted from
// the gateway's fixed emission format (json.dumps of a flat dict).
//
// Compile: C++17; no dependencies beyond POSIX sockets (-lrt for the
// tensor reader).

#pragma once

#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdexcept>
#include <string>
#include <sys/socket.h>
#include <unistd.h>

namespace ray_tpu {

struct Result {
  bool ok = false;
  std::string error;           // set when !ok
  std::string result;          // raw JSON value (plain results)
  std::string tensor_segment;  // shm name (ndarray results)
};

namespace detail {

// Extract the value of "key" from the gateway's fixed-format JSON
// envelope (json.dumps: {"k": v, ...} with double-quoted keys).  Returns
// the raw JSON token/value; strings are unescaped for the simple cases
// the gateway emits.
inline bool extract(const std::string &doc, const std::string &key,
                    std::string *out, bool *is_string) {
  const std::string needle = "\"" + key + "\":";
  size_t p = doc.find(needle);
  if (p == std::string::npos) return false;
  p += needle.size();
  while (p < doc.size() && doc[p] == ' ') ++p;
  if (p >= doc.size()) return false;
  if (doc[p] == '"') {
    ++p;
    std::string s;
    while (p < doc.size() && doc[p] != '"') {
      char c = doc[p];
      if (c == '\\' && p + 1 < doc.size()) {
        char e = doc[++p];
        switch (e) {
          case 'n': s += '\n'; break;
          case 't': s += '\t'; break;
          case 'r': s += '\r'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case '"': case '\\': case '/': s += e; break;
          case 'u': {
            // \uXXXX -> UTF-8 (json.dumps default is ensure_ascii, so
            // any non-ASCII result arrives this way).
            if (p + 4 >= doc.size()) { s += 'u'; break; }
            unsigned cp = 0;
            for (int k = 1; k <= 4; ++k) {
              char h = doc[p + k];
              cp <<= 4;
              if (h >= '0' && h <= '9') cp |= h - '0';
              else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            }
            p += 4;
            if (cp < 0x80) {
              s += static_cast<char>(cp);
            } else if (cp < 0x800) {
              s += static_cast<char>(0xC0 | (cp >> 6));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              s += static_cast<char>(0xE0 | (cp >> 12));
              s += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: s += e;
        }
        ++p;
      } else {
        s += c;
        ++p;
      }
    }
    *out = s;
    *is_string = true;
    return true;
  }
  // Non-string value: scan to the matching end at depth 0.
  int depth = 0;
  size_t start = p;
  for (; p < doc.size(); ++p) {
    char c = doc[p];
    if (c == '{' || c == '[') ++depth;
    else if (c == '}' || c == ']') {
      if (depth == 0) break;
      --depth;
    } else if (c == ',' && depth == 0) {
      break;
    } else if (c == '"') {
      ++p;
      while (p < doc.size() && doc[p] != '"') {
        if (doc[p] == '\\') ++p;
        ++p;
      }
    }
  }
  *out = doc.substr(start, p - start);
  *is_string = false;
  return true;
}

inline std::string escape(const std::string &s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out += '\\';
    out += c;
  }
  return out;
}

}  // namespace detail

class Client {
 public:
  Client(const std::string &host, int port, const std::string &token) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad host: " + host);
    if (connect(fd_, reinterpret_cast<sockaddr *>(&addr),
                sizeof(addr)) != 0)
      throw std::runtime_error("connect failed");
    send_json("{\"op\": \"auth\", \"token\": \"" +
              detail::escape(token) + "\"}");
    Result r = recv_result();
    if (!r.ok) throw std::runtime_error("gateway auth rejected");
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }
  Client(const Client &) = delete;
  Client &operator=(const Client &) = delete;

  // args_json: a JSON array string, e.g. "[2, 40]".
  std::string submit(const std::string &fn, const std::string &args_json) {
    send_json("{\"op\": \"submit\", \"fn\": \"" + detail::escape(fn) +
              "\", \"args\": " + args_json + "}");
    return expect_ref();
  }

  std::string call_actor(const std::string &actor, const std::string &ns,
                         const std::string &method,
                         const std::string &args_json) {
    std::string nsjson =
        ns.empty() ? "null" : "\"" + detail::escape(ns) + "\"";
    send_json("{\"op\": \"call_actor\", \"actor\": \"" +
              detail::escape(actor) + "\", \"namespace\": " + nsjson +
              ", \"method\": \"" + detail::escape(method) +
              "\", \"args\": " + args_json + "}");
    return expect_ref();
  }

  Result get(const std::string &ref, double timeout_s = 300.0) {
    send_json("{\"op\": \"get\", \"ref\": \"" + detail::escape(ref) +
              "\", \"timeout\": " + std::to_string(timeout_s) + "}");
    return recv_result();
  }

 private:
  std::string expect_ref() {
    Result r = recv_result();
    if (!r.ok) throw std::runtime_error("gateway error: " + r.error);
    return r.result;  // the ref hex (extracted below as "ref")
  }

  void send_json(const std::string &body) {
    uint32_t n = static_cast<uint32_t>(body.size());
    char hdr[4];
    std::memcpy(hdr, &n, 4);  // little-endian hosts (x86/arm64 LE)
    send_all(hdr, 4);
    send_all(body.data(), body.size());
  }

  void send_all(const char *p, size_t n) {
    while (n > 0) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw std::runtime_error("send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }

  void recv_all(char *p, size_t n) {
    while (n > 0) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r <= 0) throw std::runtime_error("recv failed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  Result recv_result() {
    char hdr[4];
    recv_all(hdr, 4);
    uint32_t n;
    std::memcpy(&n, hdr, 4);
    std::string body(n, '\0');
    recv_all(body.data(), n);
    Result r;
    std::string v;
    bool is_str = false;
    if (detail::extract(body, "ok", &v, &is_str)) r.ok = (v == "true");
    if (detail::extract(body, "error", &v, &is_str)) r.error = v;
    // "result" before "ref": a user result VALUE may contain a nested
    // "ref" key, but the top-level "result" key always precedes it.
    if (detail::extract(body, "result", &v, &is_str)) r.result = v;
    else if (detail::extract(body, "ref", &v, &is_str)) r.result = v;
    if (detail::extract(body, "tensor_segment", &v, &is_str))
      r.tensor_segment = v;
    return r;
  }

  int fd_ = -1;
};

}  // namespace ray_tpu
