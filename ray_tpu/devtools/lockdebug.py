"""Opt-in runtime lock instrumentation: order detector + contention
profiler.

Two modes share one set of wrappers around ``threading.Lock`` /
``threading.RLock``:

* ``RAY_TPU_DEBUG_LOCKS=1`` (``install()``) — the heavyweight
  *order detector*.  Static analysis (RT201) catches blocking calls
  lexically inside a ``with lock:`` block; orderings that only exist at
  runtime — lock A taken in one module, lock B in another, reversed on
  a third path — need instrumentation.  The debug wrappers maintain a
  per-thread stack of held locks, a process-wide acquisition-order
  graph (a new edge that closes a cycle is a potential AB/BA deadlock,
  recorded with both acquisition sites), and a patched ``time.sleep``
  that records sleeping while holding any instrumented lock.

* ``RAY_TPU_LOCK_PROFILE=1`` (``install_profile()``) — the lightweight
  *contention profiler*.  Every instrumented lock keeps per-creation-
  site wait-time and hold-time histograms (fixed log buckets), counts
  of acquires and contended acquires, and max/total times.  Stats are
  mutated only while the profiled lock itself is held, so the counters
  need no extra synchronization; the uncontended fast path costs one
  non-blocking try-acquire plus two clock reads per acquire/release
  pair.  Roughly every 64th release also publishes a sampled
  observation to the ``ray_tpu_lock_wait_seconds`` /
  ``ray_tpu_lock_hold_seconds`` catalog series (post-release, with a
  thread-local recursion guard so telemetry's own locks cannot
  re-enter).

The debug wrappers collect the same contention stats, so either mode
feeds ``contention_report()``.  Only locks created *after* install are
instrumented (the wrappers replace the constructors, not live locks).

Findings land in ``report()`` / ``contention_report()`` and are picked
up by the flight recorder (``diagnostics.write_debug_bundle`` writes
``lock_findings.json`` and ``lock_contention.json``), so a
watchdog-triggered bundle of a wedged run carries the lock story;
``ray-tpu lint --lock-report FILE`` renders the contention JSON as a
table via ``format_contention()``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import weakref
from typing import Any, Dict, List, Optional, Tuple

_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_sleep = time.sleep
_pc = time.perf_counter

_installed = False
_prof_installed = False

#: Frames of acquisition stack kept per new edge / finding.
_STACK_DEPTH = 6

#: Histogram bucket upper bounds (seconds); one overflow bucket rides
#: at the end.  Decade buckets from 1µs keep the arrays tiny (8 ints).
_PROF_BOUNDS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0)

#: Publish one sampled (wait, hold) observation to telemetry every
#: N-th release of a given lock.
_PUBLISH_EVERY = 64

#: Measured waits above this count as contended when the non-blocking
#: fast path was skipped (timeout/non-blocking acquires).
_CONTENDED_S = 1e-5


class _State:
    def __init__(self):
        self.mu = _real_Lock()
        self.seq = 0
        # edge (holder_name, acquired_name) -> info dict
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.findings: List[Dict[str, Any]] = []
        self.seen_cycles: set = set()
        self.seen_blocking: set = set()
        # (owner_tid, lock_id) for plain Locks released by a thread
        # other than their acquirer (legal handoff pattern): the owner's
        # held list is pruned lazily at its next acquire/sleep so the
        # phantom entry cannot mint bogus edges or sleep findings.
        self.foreign_released: set = set()


_state = _State()
_tls = threading.local()

# Every instrumented lock (debug or profile) registers here so
# contention_report() can aggregate per creation site.  WeakSet: dead
# locks drop out with their stats.
_reg_mu = _real_Lock()
_registry: "weakref.WeakSet" = weakref.WeakSet()


def _held() -> List[Tuple["_DebugLockBase", int]]:
    """This thread's held-lock stack: (lock, depth) entries, pruned of
    locks another thread has since released on our behalf."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    if h and _state.foreign_released:
        tid = threading.get_ident()
        with _state.mu:
            doomed = {lid for t, lid in _state.foreign_released
                      if t == tid}
            if doomed:
                _state.foreign_released -= {(tid, lid) for lid in doomed}
        if doomed:
            h[:] = [(l, d) for l, d in h if id(l) not in doomed]
    return h


def _caller_site(skip: int = 2) -> str:
    """First frame OUTSIDE this module (so with-statement acquires point
    at the user line, not at __enter__)."""
    try:
        f = sys._getframe(skip)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "<unknown>"


def _short_stack() -> List[str]:
    return [ln.strip().replace("\n", " | ")
            for ln in traceback.format_stack()[-_STACK_DEPTH - 2:-2]]


def _find_cycle(start: str, target: str) -> Optional[List[str]]:
    """Path ``start -> ... -> target`` through the edge graph (the new
    edge target->start then closes the cycle)."""
    adj: Dict[str, List[str]] = {}
    for a, b in _state.edges:
        adj.setdefault(a, []).append(b)
    path = [start]
    seen = {start}

    def dfs(node: str) -> bool:
        if node == target:
            return True
        for nxt in adj.get(node, ()):
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if dfs(nxt):
                return True
            path.pop()
        return False

    return path if dfs(start) else None


def _note_acquire(lock: "_DebugLockBase") -> None:
    held = _held()
    for i, (prev, depth) in enumerate(held):
        if prev is lock:  # reentrant re-acquire: no new ordering info
            held[i] = (prev, depth + 1)
            return
    site = _caller_site(3)
    new_edges = []
    with _state.mu:
        for prev, _depth in held:
            key = (prev.name, lock.name)
            info = _state.edges.get(key)
            if info is None:
                _state.edges[key] = {
                    "holder": prev.name, "acquired": lock.name,
                    "thread": threading.current_thread().name,
                    "site": site, "stack": _short_stack(), "count": 1}
                new_edges.append(key)
            else:
                info["count"] += 1
        for a, b in new_edges:
            # b already reaches a through older edges? then a->b closes
            # a cycle: two threads interleaving those orders deadlock.
            cycle = _find_cycle(b, a)
            if not cycle:
                continue
            cycle_key = frozenset(cycle)
            if cycle_key in _state.seen_cycles:
                continue
            _state.seen_cycles.add(cycle_key)
            _state.findings.append({
                "kind": "lock_cycle",
                "cycle": cycle + [b],
                "edges": [dict(_state.edges[e])
                          for e in _state.edges
                          if e[0] in cycle_key and e[1] in cycle_key],
                "thread": threading.current_thread().name,
                "site": site,
            })
    held.append((lock, 1))


def _note_release(lock: "_DebugLockBase") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        prev, depth = held[i]
        if prev is lock:
            if depth > 1:
                held[i] = (prev, depth - 1)
            else:
                del held[i]
            return


# -- contention stats -------------------------------------------------------


class _Stats:
    """Per-lock wait/hold accounting.  Mutated only by code that holds
    the instrumented lock (post-acquire / pre-release), so no extra
    synchronization; report-time reads are advisory snapshots.

    Cost model (the <2% overhead budget): waits are timed only on the
    CONTENDED path — the uncontended fast path's failed non-blocking
    try IS the contention detector and needs no clock, so its zero
    waits are backfilled into bucket 0 at report time.  Holds are
    timed on a 1-in-8 acquire sample (``hold_samples`` counts them);
    totals are scaled back up by the report."""

    __slots__ = ("acquires", "contended", "wait_total", "wait_max",
                 "hold_total", "hold_max", "hold_samples",
                 "wait_hist", "hold_hist", "last_wait")

    def __init__(self):
        self.acquires = 0
        self.contended = 0
        self.wait_total = 0.0
        self.wait_max = 0.0
        self.hold_total = 0.0
        self.hold_max = 0.0
        self.hold_samples = 0
        self.wait_hist = [0] * (len(_PROF_BOUNDS) + 1)
        self.hold_hist = [0] * (len(_PROF_BOUNDS) + 1)
        self.last_wait = 0.0


from bisect import bisect_left as _bidx  # noqa: E402 (bucket index)


def _maybe_publish(site: str, wait: float, hold: float) -> None:
    """Sampled telemetry publish, post-release.  The TLS guard stops
    telemetry's own (possibly instrumented) locks from re-entering."""
    if getattr(_tls, "publishing", False):
        return
    _tls.publishing = True
    try:
        from ray_tpu.util import telemetry
        tags = {"site": site}
        telemetry.observe("ray_tpu_lock_wait_seconds", wait, tags=tags)
        telemetry.observe("ray_tpu_lock_hold_seconds", hold, tags=tags)
    except Exception:
        pass
    finally:
        _tls.publishing = False


class _InstrumentedBase:
    """Shared machinery: creation-site naming, contention stats, and
    the acquire/release timing protocol.  The profile wrappers use it
    directly; the debug wrappers layer the order graph on top."""

    _kind = "Lock"

    def __init__(self):
        with _state.mu:
            _state.seq += 1
            n = _state.seq
        self._inner = self._make_inner()
        site = _caller_site(2)
        self.site = site
        self.name = f"{self._kind}#{n}@{site}"
        self._stats = _Stats()
        self._depth = 0
        self._t_acq = 0.0
        with _reg_mu:
            _registry.add(self)

    def _make_inner(self):
        return _real_Lock()

    def acquire(self, blocking=True, timeout=-1):
        # HOT PATH: an uncontended default acquire does one failed-free
        # non-blocking try, a couple of attribute ops, and (1 in 8) a
        # clock read — that's the whole <2% overhead budget.
        if blocking and timeout == -1:
            if self._inner.acquire(False):
                d = self._depth
                if d:  # reentrant re-acquire (RLock): outermost only
                    self._depth = d + 1
                    return True
                self._depth = 1
                st = self._stats
                n = st.acquires + 1
                st.acquires = n
                if not n & 7:  # sampled hold timing
                    self._t_acq = _pc()
                return True
            # Contended: the wait itself amortizes the clock reads.
            t0 = _pc()
            self._inner.acquire()
            wait = _pc() - t0
            got = True
        else:
            t0 = _pc()
            got = self._inner.acquire(blocking, timeout)
            if not got:
                return False
            wait = _pc() - t0
            d = self._depth
            if d:
                self._depth = d + 1
                return True
        self._depth = 1
        st = self._stats
        st.acquires += 1
        if wait > _CONTENDED_S:
            st.contended += 1
        st.wait_total += wait
        if wait > st.wait_max:
            st.wait_max = wait
        st.wait_hist[_bidx(_PROF_BOUNDS, wait)] += 1
        st.last_wait = wait
        self._t_acq = _pc()  # contended holds are always timed
        return got

    def release(self):
        d = self._depth - 1
        if d > 0:  # reentrant: lock stays held
            self._depth = d
            self._inner.release()
            return
        self._depth = 0
        t = self._t_acq
        if not t:  # unsampled hold: nothing to finalize
            self._inner.release()
            return
        self._t_acq = 0.0
        hold = _pc() - t
        st = self._stats
        n = st.hold_samples + 1
        st.hold_samples = n
        st.hold_total += hold
        if hold > st.hold_max:
            st.hold_max = hold
        st.hold_hist[_bidx(_PROF_BOUNDS, hold)] += 1
        self._inner.release()
        if not n & 7:  # ~every 64th acquire (1/8 of 1/8-sampled holds)
            _maybe_publish(self.site, st.last_wait, hold)

    # Condition support (RLock wrappers): finalize the hold across a
    # cond.wait() release and measure the re-acquire wait on wakeup.
    def _prof_release_save(self) -> int:
        t = self._t_acq
        if t:
            self._t_acq = 0.0
            hold = _pc() - t
            st = self._stats
            st.hold_samples += 1
            st.hold_total += hold
            if hold > st.hold_max:
                st.hold_max = hold
            st.hold_hist[_bidx(_PROF_BOUNDS, hold)] += 1
        depth = self._depth
        self._depth = 0
        return depth

    def _prof_acquire_restore(self, depth: int, wait: float) -> None:
        st = self._stats
        st.acquires += 1
        if wait > _CONTENDED_S:
            st.contended += 1
        st.wait_total += wait
        if wait > st.wait_max:
            st.wait_max = wait
        st.wait_hist[_bidx(_PROF_BOUNDS, wait)] += 1
        st.last_wait = wait
        self._t_acq = _pc()  # post-wait holds are always timed
        self._depth = depth

    # `with lock:` is THE hot usage: alias __enter__ straight to
    # acquire (the context manager protocol ignores the return value)
    # so the pair costs two Python frames, not four.
    __enter__ = acquire

    def __exit__(self, t, v, tb):
        self.release()
        return False

    def locked(self):
        fn = getattr(self._inner, "locked", None)
        return fn() if fn is not None else False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class _ProfileLock(_InstrumentedBase):
    """Contention-profiling Lock: stats only, no order graph."""

    _kind = "Lock"


class _ProfileRLock(_InstrumentedBase):
    """Contention-profiling RLock; forwards the protocol Condition uses
    so ``threading.Condition(rlock)`` keeps exact reentrant semantics."""

    _kind = "RLock"

    def _make_inner(self):
        return _real_RLock()

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        depth = self._prof_release_save()
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state):
        saved, depth = state
        t0 = _pc()
        self._inner._acquire_restore(saved)
        self._prof_acquire_restore(depth, _pc() - t0)


# -- debug (order-detector) wrappers ----------------------------------------


class _DebugLockBase(_InstrumentedBase):
    def acquire(self, blocking=True, timeout=-1):
        got = _InstrumentedBase.acquire(self, blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    # Re-alias: `__enter__ = acquire` binds the function at class-body
    # time, so each override must rebind or `with lock:` would skip it.
    __enter__ = acquire

    def release(self):
        _note_release(self)
        _InstrumentedBase.release(self)


class _DebugLock(_DebugLockBase):
    _kind = "Lock"

    # Unlike RLock, a plain Lock may legally be released by a thread
    # that did not acquire it (handoff/signal pattern).  Track the
    # acquiring thread so a foreign release queues a prune of the
    # owner's held list instead of silently leaving a phantom entry.

    def acquire(self, blocking=True, timeout=-1):
        # Wrapper delegation, not a lock acquisition of our own:
        # acquire/release pairing is the CALLER's obligation.
        got = _DebugLockBase.acquire(  # ray-tpu: noqa[RT301]
            self, blocking, timeout)
        if got:
            self._owner_ident = threading.get_ident()
        return got

    __enter__ = acquire

    def release(self):
        owner = getattr(self, "_owner_ident", None)
        self._owner_ident = None
        if owner is not None and owner != threading.get_ident():
            with _state.mu:
                _state.foreign_released.add((owner, id(self)))
            _InstrumentedBase.release(self)
        else:
            _note_release(self)
            _InstrumentedBase.release(self)


class _DebugRLock(_DebugLockBase):
    """RLock wrapper: also forwards the protocol Condition uses so
    ``threading.Condition(rlock)`` keeps exact reentrant semantics."""

    _kind = "RLock"

    def _make_inner(self):
        return _real_RLock()

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        _note_release(self)
        depth = self._prof_release_save()
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state):
        saved, depth = state
        t0 = _pc()
        self._inner._acquire_restore(saved)
        self._prof_acquire_restore(depth, _pc() - t0)
        _note_acquire(self)


def _debug_sleep(seconds):
    held = _held()
    if held:
        site = _caller_site(2)
        key = (site, tuple(l.name for l, _d in held))
        with _state.mu:
            if key not in _state.seen_blocking:
                _state.seen_blocking.add(key)
                _state.findings.append({
                    "kind": "blocking_under_lock",
                    "blocking_call": f"time.sleep({seconds!r})",
                    "held_locks": [l.name for l, _d in held],
                    "thread": threading.current_thread().name,
                    "site": site,
                    "stack": _short_stack(),
                })
    return _real_sleep(seconds)


# -- public API -------------------------------------------------------------


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` (locks created from now on are
    instrumented) and ``time.sleep``.  Idempotent.  Supersedes the
    lighter profiler: debug wrappers collect contention stats too."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _DebugLock  # type: ignore[misc]
    threading.RLock = _DebugRLock  # type: ignore[misc]
    time.sleep = _debug_sleep


def uninstall() -> None:
    """Restore the real primitives (already-created wrappers keep
    working: they delegate to real locks).  Falls back to the profile
    wrappers when the profiler is still on."""
    global _installed
    if not _installed:
        return
    _installed = False
    if _prof_installed:
        threading.Lock = _ProfileLock  # type: ignore[misc]
        threading.RLock = _ProfileRLock  # type: ignore[misc]
    else:
        threading.Lock = _real_Lock  # type: ignore[misc]
        threading.RLock = _real_RLock  # type: ignore[misc]
    time.sleep = _real_sleep


def is_installed() -> bool:
    return _installed


def install_profile() -> None:
    """Patch ``threading.Lock``/``RLock`` with the lightweight
    contention-profiling wrappers (``RAY_TPU_LOCK_PROFILE=1``).
    Idempotent; a no-op patch-wise when the heavier debug mode is
    already active (its wrappers profile too)."""
    global _prof_installed
    if _prof_installed:
        return
    _prof_installed = True
    if _installed:
        return
    threading.Lock = _ProfileLock  # type: ignore[misc]
    threading.RLock = _ProfileRLock  # type: ignore[misc]


def uninstall_profile() -> None:
    global _prof_installed
    if not _prof_installed:
        return
    _prof_installed = False
    if _installed:
        return  # debug mode still owns the constructors
    threading.Lock = _real_Lock  # type: ignore[misc]
    threading.RLock = _real_RLock  # type: ignore[misc]


def profile_installed() -> bool:
    """True when contention stats are being collected (either mode)."""
    return _prof_installed or _installed


def findings() -> List[Dict[str, Any]]:
    with _state.mu:
        return [dict(f) for f in _state.findings]


def clear() -> None:
    with _state.mu:
        _state.edges.clear()
        _state.findings.clear()
        _state.seen_cycles.clear()
        _state.seen_blocking.clear()
        _state.foreign_released.clear()
    clear_contention()


def clear_contention() -> None:
    """Reset contention stats on every live instrumented lock."""
    with _reg_mu:
        locks = list(_registry)
    for lk in locks:
        lk._stats = _Stats()


def report() -> Dict[str, Any]:
    """Snapshot for the flight recorder's ``lock_findings.json``."""
    with _state.mu:
        return {
            "installed": _installed,
            "pid": os.getpid(),
            "edges": len(_state.edges),
            "findings": [dict(f) for f in _state.findings],
        }


def contention_report(top: int = 20) -> Dict[str, Any]:
    """Aggregate per-creation-site contention stats across every live
    instrumented lock, hottest (by total wait) first.  Snapshot for
    ``lock_contention.json`` and ``ray-tpu lint --lock-report``.

    Waits were only timed on contended acquires: the report backfills
    the untimed zero-wait fast-path acquires into wait bucket 0, so
    ``sum(wait_hist) == acquires``.  Holds were timed on a 1-in-8
    sample (plus all contended holds): ``hold_samples`` is the measured
    count, ``hold_mean_s`` the unbiased-per-sample mean, and
    ``hold_total_s`` the ``mean * acquires`` estimate."""
    with _reg_mu:
        locks = list(_registry)
    agg: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for lk in locks:
        st = lk._stats
        if not st.acquires:
            continue
        row = agg.get((lk.site, lk._kind))
        if row is None:
            row = agg[(lk.site, lk._kind)] = {
                "site": lk.site, "kind": lk._kind, "locks": 0,
                "acquires": 0, "contended": 0,
                "wait_total_s": 0.0, "wait_max_s": 0.0,
                "hold_samples": 0,
                "_hold_measured_s": 0.0, "hold_max_s": 0.0,
                "wait_hist": [0] * (len(_PROF_BOUNDS) + 1),
                "hold_hist": [0] * (len(_PROF_BOUNDS) + 1),
            }
        row["locks"] += 1
        row["acquires"] += st.acquires
        row["contended"] += st.contended
        row["wait_total_s"] += st.wait_total
        row["wait_max_s"] = max(row["wait_max_s"], st.wait_max)
        row["hold_samples"] += st.hold_samples
        row["_hold_measured_s"] += st.hold_total
        row["hold_max_s"] = max(row["hold_max_s"], st.hold_max)
        for i, v in enumerate(st.wait_hist):
            row["wait_hist"][i] += v
        for i, v in enumerate(st.hold_hist):
            row["hold_hist"][i] += v
    rows = sorted(agg.values(),
                  key=lambda r: (-r["wait_total_s"], -r["acquires"]))
    for r in rows:
        r["wait_hist"][0] += r["acquires"] - sum(r["wait_hist"])
        r["wait_mean_s"] = r["wait_total_s"] / r["acquires"]
        measured = r.pop("_hold_measured_s")
        samples = r["hold_samples"]
        r["hold_mean_s"] = measured / samples if samples else 0.0
        r["hold_total_s"] = r["hold_mean_s"] * r["acquires"]
    return {
        "installed": profile_installed(),
        "pid": os.getpid(),
        "bucket_bounds_s": list(_PROF_BOUNDS),
        "total_sites": len(rows),
        "truncated": max(0, len(rows) - top),
        "sites": rows[:top],
    }


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.2f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    if v >= 1e-6:
        return f"{v * 1e6:.1f}us"
    return "0" if v <= 0 else f"{v * 1e9:.0f}ns"


def format_contention(doc: Dict[str, Any]) -> str:
    """Render a ``contention_report()`` document (e.g. a bundle's
    ``lock_contention.json``) as a top-contended-locks table."""
    sites = doc.get("sites") or []
    if not sites:
        return ("no lock contention data "
                "(profiler not installed, or no lock was acquired)")
    lines = [f"lock contention: {doc.get('total_sites', len(sites))} "
             f"site(s), pid {doc.get('pid', '?')} "
             f"(sorted by total wait)",
             f"{'site':<36} {'kind':<5} {'locks':>5} {'acquires':>9} "
             f"{'cont%':>6} {'wait total':>10} {'wait mean':>9} "
             f"{'wait max':>9} {'hold total':>10} {'hold mean':>9} "
             f"{'hold max':>9}"]
    for r in sites:
        acq = r.get("acquires") or 1
        cont = 100.0 * r.get("contended", 0) / acq
        lines.append(
            f"{r.get('site', '?')[-36:]:<36} {r.get('kind', '?'):<5} "
            f"{r.get('locks', 0):>5} {r.get('acquires', 0):>9} "
            f"{cont:>5.1f}% "
            f"{_fmt_s(r.get('wait_total_s', 0.0)):>10} "
            f"{_fmt_s(r.get('wait_mean_s', 0.0)):>9} "
            f"{_fmt_s(r.get('wait_max_s', 0.0)):>9} "
            f"{_fmt_s(r.get('hold_total_s', 0.0)):>10} "
            f"{_fmt_s(r.get('hold_mean_s', 0.0)):>9} "
            f"{_fmt_s(r.get('hold_max_s', 0.0)):>9}")
    if doc.get("truncated"):
        lines.append(f"... {doc['truncated']} more site(s) truncated")
    return "\n".join(lines)
