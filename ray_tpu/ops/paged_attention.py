"""Paged decode attention over a block-table KV cache.

The serving engine (llm/engine.py) keeps K/V in fixed-size pages,
``[num_kv_heads, total_pages, page_size, head_dim]`` per layer, with a
per-slot block table mapping sequence positions to pages.  One decode
step attends each slot's single query token over its pages.

Two execution paths, chosen statically at trace time:

- TPU: the pallas paged-attention kernel
  (jax.experimental.pallas.ops.tpu.paged_attention) — block-table-indexed
  async DMA of pages into VMEM with online softmax, so HBM traffic per
  step is the *live* KV only.  This is the kernel the reference's serving
  stack reaches through vLLM's PagedAttention CUDA ops
  (reference: python/ray/llm/_internal/serve/engines/vllm/); here the
  TPU-native analog is a pallas kernel over the same page layout.
- elsewhere (CPU tests): an exact jnp path that gathers pages and does
  dense masked attention — numerically the spec for the kernel.

Capability parity: reference vLLM engine's paged KV decode
(python/ray/llm/_internal/serve/engines/vllm/vllm_engine.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def paged_decode_attention(q, k_pages, v_pages, block_table, seq_lens,
                           page_size: int, *,
                           pages_per_compute_block: int = 8):
    """One decode step of attention over the paged cache.

    q: [B, H, D] (one new token per slot); k_pages/v_pages:
    [Hkv, NP, page, D]; block_table: [B, P] page ids; seq_lens: [B]
    sequence length INCLUDING the new token.  Returns [B, H, D].
    """
    from .attention import _on_tpu
    if _on_tpu():
        return _pallas_path(q, k_pages, v_pages, block_table, seq_lens,
                            page_size, pages_per_compute_block)
    return _exact_path(q, k_pages, v_pages, block_table, seq_lens, page_size)


def _pallas_path(q, k_pages, v_pages, block_table, seq_lens, page_size: int,
                 pages_per_compute_block: int):
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention)

    D = q.shape[-1]
    P = block_table.shape[1]
    # The kernel applies no softmax scale; fold 1/sqrt(D) into q.
    q_scaled = (q.astype(jnp.float32) / math.sqrt(D)).astype(q.dtype)
    block = min(pages_per_compute_block, P)
    while P % block:
        block -= 1
    out = paged_attention(
        q_scaled, k_pages, v_pages,
        lengths=seq_lens.astype(jnp.int32),
        page_indices=block_table.astype(jnp.int32),
        pages_per_compute_block=block,
    )
    return out.astype(q.dtype)


def _exact_path(q, k_pages, v_pages, block_table, seq_lens, page_size: int):
    """Reference semantics: gather each sequence's pages and run dense
    masked attention.  Materializes [B, H, S_max, D] — fine for CPU tests,
    never the TPU path."""
    B, H, D = q.shape
    Hkv = k_pages.shape[0]
    P = block_table.shape[1]
    group = H // Hkv
    k = jnp.take(k_pages, block_table, axis=1)   # [Hkv, B, P, page, D]
    v = jnp.take(v_pages, block_table, axis=1)
    k = k.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, P * page_size, D)
    v = v.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, P * page_size, D)
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(D)
    kv_pos = jnp.arange(P * page_size)
    mask = kv_pos[None, :] < seq_lens[:, None]          # [B, S_max]
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhk,bhkd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
