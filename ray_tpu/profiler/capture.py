"""Worker/driver-side profile capture: host sampling + jax.profiler.

One half of the on-demand cluster profiler (the other half — fan-out,
collection and merging — lives in ``_private/runtime.py`` and
``profiler/merge.py``).  ``capture_profile`` runs IN the profiled
process: a pure-Python sampling profiler walks ``sys._current_frames()``
at a fixed rate (no py-spy dependency, works in any interpreter we own),
and optionally brackets the window with ``jax.profiler``
start_trace/stop_trace so the XLA-level TensorBoard artifacts ride along.

Clock alignment: the ProfileRequest carries the driver's wall clock at
send time; the capturing process records ``clock_offset_s = local_wall -
driver_wall`` at receipt (bounded above by transit time), and the merger
shifts every event by ``-clock_offset_s`` so the merged trace is in
driver-clock coordinates.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

#: One capture at a time per process: jax.profiler is process-global and
#: overlapping samplers would double the sampling load mid-incident.
_active_lock = threading.Lock()

#: Cap on jax artifact bytes shipped driver-ward per capture (the
#: TensorBoard xplane protos are usually ~100KB on small programs but can
#: balloon; past the cap the files stay on the worker and only their
#: paths are reported).
MAX_JAX_ARTIFACT_BYTES = 8 * 1024 * 1024


def _thread_names() -> Dict[int, str]:
    names: Dict[int, str] = {}
    for t in threading.enumerate():
        if t.ident is not None:
            names[t.ident] = t.name
    return names


def _sample_once(skip_ident: int, max_depth: int = 12) -> Dict[int, Dict]:
    """One ``sys._current_frames()`` snapshot: per-thread leaf frame plus
    a bounded stack of ``func (file:line)`` strings, innermost first."""
    out: Dict[int, Dict] = {}
    for tid, frame in sys._current_frames().items():
        if tid == skip_ident:
            continue  # never profile the sampler itself
        stack: List[str] = []
        f = frame
        while f is not None and len(stack) < max_depth:
            code = f.f_code
            stack.append(f"{code.co_name} "
                         f"({os.path.basename(code.co_filename)}:"
                         f"{f.f_lineno})")
            f = f.f_back
        if stack:
            out[tid] = {"leaf": stack[0], "stack": stack}
    return out


def _run_sampler(duration_s: float, hz: float,
                 samples: List[Dict[str, Any]]) -> None:
    period = 1.0 / max(1.0, hz)
    ident = threading.get_ident()
    deadline = time.monotonic() + max(0.0, duration_s)
    names = _thread_names()
    refreshed = time.monotonic()
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        threads = _sample_once(ident)
        now_wall = time.time()
        if t0 - refreshed > 0.5:  # new threads appear mid-capture
            names = _thread_names()
            refreshed = t0
        samples.append({
            "t": now_wall,
            "threads": {tid: dict(rec, name=names.get(tid, f"t{tid}"))
                        for tid, rec in threads.items()},
        })
        sleep = period - (time.monotonic() - t0)
        if sleep > 0:
            time.sleep(sleep)


def _jax_profile_window(duration_s: float) -> Dict[str, Any]:
    """Bracket ``duration_s`` with jax.profiler and collect the artifact
    files.  Only runs when jax is ALREADY imported in this process — a
    profile capture must never be the thing that pulls jax into a worker
    that wasn't using it."""
    info: Dict[str, Any] = {"attempted": False, "files": {}, "error": None}
    if "jax" not in sys.modules:
        info["error"] = "jax not imported in this process"
        return info
    import shutil
    import tempfile

    import jax
    tmpdir = tempfile.mkdtemp(prefix="ray_tpu_jaxprof_")
    info["attempted"] = True
    try:
        jax.profiler.start_trace(tmpdir)
        time.sleep(max(0.0, duration_s))
        jax.profiler.stop_trace()
        total = 0
        for root, _dirs, files in os.walk(tmpdir):
            for fname in files:
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, tmpdir)
                size = os.path.getsize(full)
                if total + size > MAX_JAX_ARTIFACT_BYTES:
                    info["error"] = (f"artifacts exceed "
                                     f"{MAX_JAX_ARTIFACT_BYTES}B cap; "
                                     f"truncated")
                    break
                with open(full, "rb") as f:
                    info["files"][rel] = f.read()
                total += size
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        info["error"] = f"{type(e).__name__}: {e}"
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return info


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device memory stats from jax (empty when jax isn't loaded or
    the backend doesn't report them — CPU usually doesn't)."""
    if "jax" not in sys.modules:
        return []
    out: List[Dict[str, Any]] = []
    try:
        import jax
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            out.append({
                "device": str(d),
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            })
    except Exception:  # noqa: BLE001 — stats are garnish
        return out
    return out


def capture_profile(worker_id: str, duration_s: float,
                    hz: float = 67.0, jax_profile: bool = False,
                    driver_wall_s: Optional[float] = None,
                    is_driver: bool = False) -> Dict[str, Any]:
    """Profile THIS process for ``duration_s``; returns the capture
    record shipped to the driver (see merge.py for the shape consumed).
    Blocks for the duration — callers run it off the receive thread."""
    recv_wall = time.time()
    # Wall-minus-wall on purpose: this measures the CLOCK OFFSET between
    # two hosts (monotonic clocks have unrelated bases across processes).
    offset = 0.0
    if driver_wall_s:
        offset = recv_wall - driver_wall_s  # ray-tpu: noqa[RT203]
    if not _active_lock.acquire(blocking=False):
        return {"worker_id": worker_id, "pid": os.getpid(),
                "is_driver": is_driver, "error": "capture already running",
                "clock_offset_s": offset, "samples": []}
    try:
        samples: List[Dict[str, Any]] = []
        if jax_profile:
            # The jax window sleeps for the duration, so the host sampler
            # runs on its own thread alongside it.
            box: Dict[str, Any] = {}

            def sample():
                _run_sampler(duration_s, hz, samples)
            from ray_tpu._private import sanitizer
            t = sanitizer.spawn(sample, name="profile-sampler")
            box["jax"] = _jax_profile_window(duration_s)
            t.join(timeout=duration_s + 5.0)
            jax_info = box["jax"]
        else:
            _run_sampler(duration_s, hz, samples)
            jax_info = {"attempted": False, "files": {}, "error": None}
        return {
            "worker_id": worker_id,
            "pid": os.getpid(),
            "is_driver": is_driver,
            "clock_offset_s": offset,
            "duration_s": duration_s,
            "hz": hz,
            "samples": samples,
            "jax_profile": jax_info,
            "memory": device_memory_stats(),
            "error": None,
        }
    finally:
        _active_lock.release()
