// ray_tpu C++ user API: zero-copy reads of arena-store objects.
//
// Reference analog: cpp/ (the C++ user API, ray::Get over the plasma
// client).  Scope here is the data plane: a C++ program maps the node's
// shared-memory arena (or a dedicated per-object segment) and reads a
// sealed object's payload in place — the same zero-copy view Python
// workers get.  Payload layout (ray_tpu/_private/serialization.py):
//
//   u32  n_buffers          (little endian)
//   u64  len_meta
//   meta bytes              (cloudpickle; opaque to C++)
//   n_buffers x { u64 len; raw bytes }
//
// The out-of-band buffers are raw array bytes (numpy buffers land here
// unpickled), so a C++ consumer that knows its schema by contract (e.g.
// "one float32 buffer") reads tensors with zero copies and no Python.
// Task/actor submission from C++ is future work; descriptors travel to
// the C++ side through any channel (CLI args, files, sockets).
//
// Usage:
//   ray_tpu::ObjectView v = ray_tpu::open_object(segment, offset, nbytes);
//   const float* xs = reinterpret_cast<const float*>(v.buffers[0].data);
//
// Compile: C++17, -lrt on Linux.

#pragma once

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <stdexcept>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace ray_tpu {

struct BufferView {
  const uint8_t *data;
  uint64_t size;
};

struct ObjectView {
  // Keeps the mapping alive; unmapped on destruction.
  void *map_base = nullptr;
  size_t map_len = 0;
  const uint8_t *meta = nullptr;
  uint64_t meta_len = 0;
  std::vector<BufferView> buffers;

  ObjectView() = default;
  ObjectView(ObjectView &&o) noexcept { *this = std::move(o); }
  ObjectView &operator=(ObjectView &&o) noexcept {
    if (this != &o) {
      release();
      map_base = o.map_base;
      map_len = o.map_len;
      meta = o.meta;
      meta_len = o.meta_len;
      buffers = std::move(o.buffers);
      o.map_base = nullptr;
      o.map_len = 0;
    }
    return *this;
  }
  ObjectView(const ObjectView &) = delete;
  ObjectView &operator=(const ObjectView &) = delete;
  ~ObjectView() { release(); }

  void release() {
    if (map_base != nullptr) {
      ::munmap(map_base, map_len);
      map_base = nullptr;
    }
  }
};

namespace detail {
inline uint64_t read_u64(const uint8_t *p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64/arm64)
}
inline uint32_t read_u32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
}  // namespace detail

// Map `segment` (a POSIX shm name as Python reports it, no leading '/')
// and parse the payload at [offset, offset+nbytes).  Matches descriptors
// ("shma", segment, offset, nbytes, id) from the arena store and
// ("shm", name, nbytes) dedicated segments (use offset 0).
inline ObjectView open_object(const std::string &segment, uint64_t offset,
                              uint64_t nbytes) {
  std::string name = segment.empty() || segment[0] == '/'
                         ? segment
                         : "/" + segment;
  int fd = ::shm_open(name.c_str(), O_RDONLY, 0);
  if (fd < 0) {
    throw std::runtime_error("shm_open failed for " + name);
  }
  struct stat st;
  if (::fstat(fd, &st) != 0 ||
      static_cast<uint64_t>(st.st_size) < offset + nbytes) {
    ::close(fd);
    throw std::runtime_error("segment smaller than descriptor range");
  }
  void *base = ::mmap(nullptr, offset + nbytes, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) {
    throw std::runtime_error("mmap failed for " + name);
  }

  ObjectView v;
  v.map_base = base;
  v.map_len = offset + nbytes;
  const uint8_t *p = static_cast<const uint8_t *>(base) + offset;
  const uint8_t *end = p + nbytes;
  if (nbytes < 12) {
    throw std::runtime_error("payload shorter than header");
  }
  uint32_t n_buffers = detail::read_u32(p);
  uint64_t len_meta = detail::read_u64(p + 4);
  p += 12;
  if (p + len_meta > end) {
    throw std::runtime_error("corrupt payload: meta overruns");
  }
  v.meta = p;
  v.meta_len = len_meta;
  p += len_meta;
  for (uint32_t i = 0; i < n_buffers; ++i) {
    if (p + 8 > end) {
      throw std::runtime_error("corrupt payload: buffer length overruns");
    }
    uint64_t blen = detail::read_u64(p);
    p += 8;
    if (p + blen > end) {
      throw std::runtime_error("corrupt payload: buffer overruns");
    }
    v.buffers.push_back(BufferView{p, blen});
    p += blen;
  }
  return v;
}

}  // namespace ray_tpu
