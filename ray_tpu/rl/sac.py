"""SAC: soft actor-critic for continuous control.

Reference: rllib/algorithms/sac/ (SACConfig, SAC training_step: env step ->
replay -> twin-Q TD update with entropy bonus -> policy update -> alpha
update -> polyak target sync).  Here the whole update — critic, actor,
temperature, target polyak — is one jitted function of (state, batch, key),
the XLA-friendly shape for TPU training: no Python between the four
optimizer steps, so the compiler fuses them into a single program.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .replay_buffer import ReplayBuffer
from .rl_module import ContinuousModuleSpec, GaussianPolicyModule, TwinQModule


class SACState(NamedTuple):
    pi_params: Any
    q_params: Any
    q_target: Any
    log_alpha: Any
    pi_opt: Any
    q_opt: Any
    alpha_opt: Any


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self.buffer_size = 100_000
        self.learning_starts = 500
        self.tau = 0.005            # polyak coefficient
        self.train_batch_size = 256
        self.updates_per_step = 1
        self.initial_alpha = 0.2
        self.target_entropy = None  # default: -action_dim
        self.actor_lr = None        # default: lr
        self.critic_lr = None
        self.alpha_lr = 3e-4

    def training(self, *, buffer_size=None, learning_starts=None, tau=None,
                 updates_per_step=None, initial_alpha=None,
                 target_entropy=None, actor_lr=None, critic_lr=None,
                 alpha_lr=None, **kw) -> "SACConfig":
        super().training(**kw)
        for name, val in (("buffer_size", buffer_size),
                          ("learning_starts", learning_starts),
                          ("tau", tau),
                          ("updates_per_step", updates_per_step),
                          ("initial_alpha", initial_alpha),
                          ("target_entropy", target_entropy),
                          ("actor_lr", actor_lr),
                          ("critic_lr", critic_lr),
                          ("alpha_lr", alpha_lr)):
            if val is not None:
                setattr(self, name, val)
        return self


class SAC(Algorithm):
    """Off-policy; drives its own env loop like DQN."""

    _use_env_runner_group = False

    def setup(self, config: SACConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        env = make_env(config.env_spec)
        if not env.is_continuous:
            raise ValueError("SAC requires a continuous-action env "
                             "(set env.action_dim)")
        self.env = env
        spec = ContinuousModuleSpec(env.observation_dim, env.action_dim,
                                    env.action_low, env.action_high,
                                    tuple(config.module_hidden))
        self.pi = GaussianPolicyModule(spec)
        self.q = TwinQModule(spec)
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(env.action_dim))
        actor_lr = config.actor_lr or config.lr
        critic_lr = config.critic_lr or config.lr
        pi_optim = optax.adam(actor_lr)
        q_optim = optax.adam(critic_lr)
        alpha_optim = optax.adam(config.alpha_lr)
        gamma, tau = config.gamma, config.tau

        key = jax.random.key(config.seed)
        kp, kq = jax.random.split(key)
        pi_params = self.pi.init(kp)
        q_params = self.q.init(kq)
        log_alpha = jnp.log(jnp.asarray(config.initial_alpha, jnp.float32))
        self.state = SACState(
            pi_params, q_params, q_params, log_alpha,
            pi_optim.init(pi_params), q_optim.init(q_params),
            alpha_optim.init(log_alpha))

        pi, q = self.pi, self.q

        def update(state: SACState, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(state.log_alpha)

            # -- critic: soft TD target from the target twin (clipped) ----
            next_a, next_logp = pi.sample(state.pi_params,
                                          batch["next_obs"], k1)
            tq1, tq2 = q.q_values(state.q_target, batch["next_obs"], next_a)
            next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + gamma * \
                (1.0 - batch["terminateds"]) * next_v
            target = jax.lax.stop_gradient(target)

            def critic_loss(qp):
                q1, q2 = q.q_values(qp, batch["obs"], batch["actions"])
                return jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2), \
                    (jnp.mean(q1), jnp.mean(jnp.abs(q1 - target)))

            (closs, (q_mean, td_abs)), q_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state.q_params)
            q_updates, q_opt = q_optim.update(q_grads, state.q_opt,
                                              state.q_params)
            q_params = optax.apply_updates(state.q_params, q_updates)

            # -- actor: maximize E[min Q - alpha log pi] ------------------
            def actor_loss(pp):
                a, logp = pi.sample(pp, batch["obs"], k2)
                q1, q2 = q.q_values(q_params, batch["obs"], a)
                return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), \
                    jnp.mean(logp)

            (aloss, logp_mean), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state.pi_params)
            pi_updates, pi_opt = pi_optim.update(pi_grads, state.pi_opt,
                                                 state.pi_params)
            pi_params = optax.apply_updates(state.pi_params, pi_updates)

            # -- temperature: drive entropy toward the target -------------
            def alpha_loss(la):
                return -jnp.exp(la) * jax.lax.stop_gradient(
                    logp_mean + target_entropy)

            al, a_grads = jax.value_and_grad(alpha_loss)(state.log_alpha)
            a_updates, alpha_opt = alpha_optim.update(a_grads,
                                                      state.alpha_opt)
            log_alpha = optax.apply_updates(state.log_alpha, a_updates)

            # -- polyak target sync ---------------------------------------
            q_target = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                    state.q_target, q_params)
            metrics = {"critic_loss": closs, "actor_loss": aloss,
                       "alpha": alpha, "q_mean": q_mean,
                       "td_abs": td_abs, "logp_mean": logp_mean}
            return SACState(pi_params, q_params, q_target, log_alpha,
                            pi_opt, q_opt, alpha_opt), metrics

        self._update = jax.jit(update)
        self._sample_act = jax.jit(pi.sample)
        self._infer_act = jax.jit(pi.forward_inference)

        self.buffer = ReplayBuffer(config.buffer_size, seed=config.seed)
        self._key = jax.random.key(config.seed + 1)
        self._obs, _ = self.env.reset(seed=config.seed)
        self._steps = 0
        self._rng = np.random.default_rng(config.seed)
        self._ep_return = 0.0
        self._returns: list = []

    def _act(self, obs: np.ndarray) -> np.ndarray:
        import jax
        cfg: SACConfig = self.config
        if self._steps < cfg.learning_starts:
            # Warmup: uniform random actions across the bounds.
            return self._rng.uniform(
                self.env.action_low, self.env.action_high,
                self.env.action_dim).astype(np.float32)
        self._key, sub = jax.random.split(self._key)
        a, _ = self._sample_act(self.state.pi_params, obs[None], sub)
        return np.asarray(a)[0]

    def training_step(self) -> Dict[str, Any]:
        import jax
        cfg: SACConfig = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.rollout_fragment_length):
            action = self._act(self._obs)
            next_obs, r, term, trunc, _ = self.env.step(action)
            self.buffer.add(
                obs=self._obs[None], actions=action[None].astype(np.float32),
                rewards=np.array([r], np.float32), next_obs=next_obs[None],
                terminateds=np.array([float(term)], np.float32))
            self._ep_return += r
            self._steps += 1
            if term or trunc:
                self._returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = next_obs
            if self._steps >= cfg.learning_starts and \
                    self._steps % cfg.updates_per_step == 0:
                batch = self.buffer.sample(cfg.train_batch_size)
                self._key, sub = jax.random.split(self._key)
                self.state, m = self._update(self.state, batch, sub)
                # ONE transfer for the metrics dict, not one per value.
                m = jax.device_get(m)
                metrics = {k: float(v) for k, v in m.items()}
        recent = self._returns[-100:]
        return {
            "learner": metrics,
            "num_env_steps_sampled": self._steps,
            "buffer_size": len(self.buffer),
            "env_runners": {
                "episode_return_mean":
                    float(np.mean(recent)) if recent else float("nan"),
                "num_episodes": len(self._returns),
            },
        }

    def get_weights(self):
        return {"pi": self.state.pi_params, "q": self.state.q_params,
                "q_target": self.state.q_target,
                "log_alpha": self.state.log_alpha}

    def set_weights(self, params) -> None:
        self.state = self.state._replace(
            pi_params=params["pi"], q_params=params["q"],
            q_target=params["q_target"], log_alpha=params["log_alpha"])

    def compute_single_action(self, obs: np.ndarray,
                              explore: bool = False) -> np.ndarray:
        import jax
        if explore:
            self._key, sub = jax.random.split(self._key)
            a, _ = self._sample_act(self.state.pi_params, obs[None], sub)
            return np.asarray(a)[0]
        return np.asarray(self._infer_act(self.state.pi_params,
                                          obs[None]))[0]
