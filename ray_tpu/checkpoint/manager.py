"""Checkpoint lifecycle: worker-side save client, coordinator-side manager.

Two halves of one commit protocol:

* ``WorkerCheckpointClient`` runs inside each train worker.  ``save()``
  blocks only for the device->host snapshot (plus backpressure when the
  bounded write queue is full); the writer thread publishes the rank's
  shard pair, pushes the emergency replica, and acks the coordinator over
  the runtime KV store.
* ``CheckpointManager`` runs in the driver/controller.  It collects acks
  and, once EVERY rank of a step has acked, builds + commits the global
  ``manifest.json`` via tmp-file + atomic rename, registers the entry,
  enforces retention, and garbage-collects dead uncommitted directories.
  A checkpoint that was never committed is invisible to ``latest()`` —
  a crash mid-save can never be mistaken for a valid checkpoint.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from ..util import telemetry
from . import format as ckpt_format
from . import replica as replica_mod
from .async_writer import AsyncCheckpointWriter, WriteJob, publish_shard

_STEP_DIR_RE = re.compile(r"^checkpoint_(\d+)$")


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"checkpoint_{step:06d}")


def _dir_step(name: str) -> Optional[int]:
    m = _STEP_DIR_RE.match(name)
    return int(m.group(1)) if m else None


def ack_prefix(run_id: str) -> str:
    """KV namespace the coordinator polls for shard acks."""
    return f"train/{run_id}/ckpt/"


def ack_key(run_id: str, step: int, rank: int, nonce: str) -> str:
    # The nonce is unique per worker incarnation: a restarted rank
    # re-saving the same step acks at a FRESH key, so the coordinator's
    # seen-key dedup can never hide the new ack behind the dead one.
    return f"{ack_prefix(run_id)}{step:08d}/{rank}/{nonce}"


class Checkpoint:
    """Handle to a checkpoint directory (reference: train/_checkpoint.py:56).

    Understands both the sharded v1 layout (``manifest.json``) and the
    legacy single-pickle layout (``pytree.pkl``).
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Materialize a copy of the checkpoint at ``dest``.

        The copy lands in a staging dir next to the target and is
        published with one atomic rename: a reader (or a crash) can never
        observe a half-copied directory at ``dest``.
        """
        dest = os.path.abspath(dest or tempfile.mkdtemp(prefix="ckpt_"))
        if dest == self.path:
            return dest
        parent = os.path.dirname(dest) or "."
        os.makedirs(parent, exist_ok=True)
        staging = tempfile.mkdtemp(prefix=os.path.basename(dest) + ".tmp",
                                   dir=parent)
        try:
            # copytree into the (empty) staging dir, then swing it in.
            shutil.copytree(self.path, staging, dirs_exist_ok=True)
            try:
                os.replace(staging, dest)
            except OSError:
                # dest already exists (mkdtemp pre-created it, or a prior
                # copy landed): atomically swap it out of the namespace
                # first, then retire the old tree.
                old = tempfile.mkdtemp(prefix=os.path.basename(dest)
                                       + ".old", dir=parent)
                os.replace(dest, os.path.join(old, "d"))
                os.replace(staging, dest)
                shutil.rmtree(old, ignore_errors=True)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        return dest

    # -- pytree convenience -------------------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str,
                    use_orbax: bool = False) -> "Checkpoint":
        os.makedirs(path, exist_ok=True)
        ckpt_format.save_pytree(tree, path, use_orbax=use_orbax)
        return cls(path)

    def load_pytree(self, use_orbax: bool = False,
                    placement: Optional[Callable] = None) -> Any:
        if placement is not None:
            return ckpt_format.restore_tree(self.path, placement=placement)
        return ckpt_format.load_pytree(self.path, use_orbax=use_orbax)

    def manifest(self) -> Optional[Dict[str, Any]]:
        try:
            return ckpt_format.read_manifest(self.path)
        except (FileNotFoundError, ckpt_format.CheckpointError):
            return None

    def validate(self, deep: bool = False) -> List[str]:
        return ckpt_format.verify_checkpoint(self.path, deep=deep)

    def __repr__(self):
        return f"Checkpoint({self.path})"


def atomic_rmtree(path: str) -> None:
    """Delete a directory so no reader can race with a half-deleted tree:
    one atomic rename takes it out of the namespace, then the rename
    target is reaped at leisure."""
    if not os.path.isdir(path):
        return
    doomed = f"{path}.deleting-{os.getpid()}-{time.monotonic_ns()}"
    try:
        os.replace(path, doomed)
    except OSError:
        # Concurrent deleter won the rename; nothing left to do.
        return
    shutil.rmtree(doomed, ignore_errors=True)


class CheckpointManager:
    """Tracks committed checkpoints under <storage>/<experiment>/.

    Commit protocol, sharded path: per-rank acks land via ``note_ack``;
    ``commit_ready()`` writes the manifest once a step has a full ack set
    (coordinator-side; reference analog: checkpoint_manager.py
    rank-0-commit, upgraded to all-rank barrier + atomic manifest).
    The legacy path (``register`` from a rank-0 report) still works.
    """

    def __init__(self, storage_path: str, experiment_name: str,
                 num_to_keep: Optional[int] = None):
        self.root = os.path.normpath(
            os.path.join(os.path.abspath(storage_path), experiment_name))
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self._index_path = os.path.join(self.root, "checkpoints.json")
        self._entries: List[Dict[str, Any]] = []
        if os.path.exists(self._index_path):
            with open(self._index_path) as f:
                self._entries = json.load(f)
        #: step -> {rank -> ack payload} for the sharded commit protocol.
        self._acks: Dict[int, Dict[int, Dict[str, Any]]] = {}
        self._committed: set = set()
        self._failed_steps: set = set()
        #: Current worker-group generation; acks tagged with another
        #: generation are ignored (set via reset_pending_acks).
        self._generation: Optional[int] = None
        for e in self._entries:
            if e.get("step") is not None:
                self._committed.add(int(e["step"]))

    def checkpoint_dir(self, step: int) -> str:
        return step_dir(self.root, step)

    # -- sharded commit protocol -------------------------------------------

    def note_ack(self, payload: Dict[str, Any]) -> None:
        step = int(payload["step"])
        if step in self._committed:
            return
        # A dead group's straggler ack (its writer thread raced the
        # teardown) must not join the current generation's ack set.
        gen = payload.get("generation")
        if gen is not None and self._generation is not None and \
                gen != self._generation:
            return
        self._acks.setdefault(step, {})[int(payload["rank"])] = payload

    def reset_pending_acks(self, generation: Optional[int] = None) -> None:
        """Drop every uncommitted ack set.  Called on group re-formation
        (failure recovery / elastic resize): a retried step must commit
        only from a COMPLETE ack set of the new incarnation — mixing a
        dead incarnation's acks with the new one's would commit a
        manifest spanning two divergent training timelines (and race the
        new incarnation's in-flight shard rewrites)."""
        self._acks.clear()
        self._failed_steps.clear()
        self._generation = generation

    def commit_ready(self) -> List[Dict[str, Any]]:
        """Commit every step whose full ack set has arrived; returns the
        freshly committed manifests (in step order)."""
        out: List[Dict[str, Any]] = []
        for step in sorted(self._acks):
            if step in self._committed or step in self._failed_steps:
                continue
            acks = self._acks[step]
            world = int(next(iter(acks.values()))["world"])
            if len(acks) < world:
                continue
            dirpath = acks[min(acks)]["dir"]
            rank0 = acks.get(0, {})
            # Manifest metrics must be JSON-clean: numpy scalars (the
            # normal type of a jax loss) would raise out of json.dumps.
            metrics = _scalar_metrics(rank0.get("metrics") or {})
            try:
                manifest = ckpt_format.build_manifest(
                    dirpath, step, world, metrics=metrics,
                    replica=any(a.get("replica") for a in acks.values()))
                ckpt_format.commit_manifest(dirpath, manifest)
            except Exception as e:  # noqa: BLE001 — a commit failure
                # must fail the STEP, never the training run.  The step
                # stays invisible to latest() and is GC'd later.
                telemetry.note_swallowed("checkpoint.commit", e)
                self._failed_steps.add(step)
                continue
            self._committed.add(step)
            self._register_entry({
                "path": os.path.abspath(dirpath),
                "metrics": metrics,
                "time": time.time(),
                "step": step,
                "world_size": world,
                "total_bytes": manifest["total_bytes"],
                "replica": manifest["replica"],
            })
            out.append(manifest)
        if out:
            self.gc_uncommitted()
            for step in list(self._acks):
                if step in self._committed:
                    del self._acks[step]
        return out

    def gc_uncommitted(self) -> List[str]:
        """Reap checkpoint dirs that can no longer commit: older than the
        newest committed step, no manifest, not registered.  Newer
        uncommitted dirs are in-flight saves and must be left alone."""
        if not self._committed:
            return []
        horizon = max(self._committed)
        known = {e["path"] for e in self._entries}
        reaped: List[str] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            step = _dir_step(name)
            path = os.path.join(self.root, name)
            if step is None or step >= horizon or path in known:
                continue
            if ckpt_format.is_committed(path):
                continue
            atomic_rmtree(path)
            reaped.append(path)
        return reaped

    # -- legacy commit (rank-0 report) --------------------------------------

    def register(self, path: str, metrics: Dict[str, Any]) -> None:
        self._register_entry({
            "path": os.path.abspath(path),
            "metrics": _scalar_metrics(metrics),
            "time": time.time(),
        })

    def _register_entry(self, entry: Dict[str, Any]) -> None:
        self._entries.append(entry)
        self._flush()
        self._enforce_retention()

    # -- queries ------------------------------------------------------------

    def latest(self) -> Optional[str]:
        return self._entries[-1]["path"] if self._entries else None

    def best(self, metric: str, mode: str = "min") -> Optional[str]:
        scored = [e for e in self._entries if metric in e["metrics"]]
        if not scored:
            return None
        pick = min if mode == "min" else max
        return pick(scored, key=lambda e: e["metrics"][metric])["path"]

    def all_entries(self) -> List[Dict[str, Any]]:
        return list(self._entries)

    def _flush(self) -> None:
        ckpt_format.write_bytes_atomic(
            self._index_path, json.dumps(self._entries, indent=1).encode())

    def _enforce_retention(self) -> None:
        if not self.num_to_keep:
            return
        while len(self._entries) > self.num_to_keep:
            victim = self._entries.pop(0)
            self._flush()
            atomic_rmtree(victim["path"])


def _validated_blobs(blobs: Dict[int, Any],
                     manifest: Dict[str, Any]) -> Dict[int, Any]:
    """Keep only in-memory shards whose bytes match the COMMITTED
    manifest.  A replica keyed by (rank, step) can be stale — a dead
    incarnation's divergent save attempt for the same step whose
    re-push was lost — and must fall back to disk, not restore silently
    wrong weights."""
    import zlib
    by_rank = {sh["rank"]: sh for sh in manifest["shards"]}
    out: Dict[int, Any] = {}
    for rank, (index, blob) in blobs.items():
        sh = by_rank.get(rank)
        if sh is None or len(blob) != sh["nbytes"] or \
                (zlib.crc32(blob) & 0xFFFFFFFF) != sh["crc32"]:
            telemetry.note_swallowed(
                "checkpoint.replica.stale_blob",
                ckpt_format.CheckpointError(
                    f"rank {rank} replica blob does not match the "
                    f"committed manifest; using disk"))
            continue
        out[rank] = (index, blob)
    return out


def _scalar_metrics(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe scalar subset of user metrics (numpy scalars coerced:
    np.float32 is not a python float and would crash json.dumps)."""
    out: Dict[str, Any] = {}
    for k, v in metrics.items():
        if isinstance(v, bool) or isinstance(v, (str,)):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = v
        elif hasattr(v, "item") and getattr(v, "shape", None) in ((), None):
            try:
                item = v.item()
            except Exception:
                continue
            if isinstance(item, (int, float, bool, str)):
                out[k] = item
    return out


def scan_run_dir(root: str, deep: bool = False) -> List[Dict[str, Any]]:
    """Filesystem view of a run directory for ``ray-tpu ckpt ls``: every
    ``checkpoint_*`` dir with step, size, shard count, replica presence
    and validity — committed or not."""
    out: List[Dict[str, Any]] = []
    try:
        # Numeric step order, not lexicographic: zero-padding overflows
        # past step 999999 and would mis-sort "newest".
        names = sorted(os.listdir(root),
                       key=lambda n: (_dir_step(n) is None,
                                      _dir_step(n) or 0, n))
    except OSError as e:
        raise ckpt_format.CheckpointError(f"cannot list {root}: {e}")
    for name in names:
        step = _dir_step(name)
        if step is None:
            continue
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        rec: Dict[str, Any] = {"path": path, "step": step}
        problems = ckpt_format.verify_checkpoint(path, deep=deep)
        committed = ckpt_format.is_committed(path)
        rec["committed"] = committed
        rec["valid"] = committed and not problems
        rec["problems"] = problems
        manifest = None
        if committed:
            try:
                manifest = ckpt_format.read_manifest(path)
            except ckpt_format.CheckpointError:
                manifest = None
        if manifest is not None:
            rec.update(shards=len(manifest["shards"]),
                       world_size=manifest["world_size"],
                       bytes=manifest["total_bytes"],
                       replica=manifest["replica"],
                       time=manifest["time"],
                       metrics=manifest.get("metrics", {}))
        else:
            rec.update(shards=sum(
                1 for f in os.listdir(path) if f.endswith(".index.json")),
                bytes=sum(os.path.getsize(os.path.join(path, f))
                          for f in os.listdir(path)
                          if f.endswith(".bin")),
                replica=False)
        out.append(rec)
    return out


# -- worker-side save client -------------------------------------------------


class WorkerCheckpointClient:
    """Per-train-worker save/restore client (owned by the TrainContext)."""

    def __init__(self, run_id: str, rank: int, world_size: int,
                 run_root: str, experiment: str,
                 async_save: bool = True, max_inflight: int = 2,
                 emergency_replica: bool = False,
                 initial_step: int = 0,
                 generation: Optional[int] = None):
        self.run_id = run_id
        self.rank = rank
        self.world_size = world_size
        self.run_root = run_root
        self.experiment = experiment
        self.async_save = async_save
        self.emergency_replica = emergency_replica
        self.generation = generation
        self._writer: Optional[AsyncCheckpointWriter] = None
        self._max_inflight = max_inflight
        self._holder = None
        self._holder_resolved = False
        self._local_pin = replica_mod.LocalPin(experiment, rank) \
            if emergency_replica else None
        # Auto-step sequence: a restarted worker resumes PAST the
        # checkpoint it restored from, never over it.
        self._step_seq = initial_step
        # Incarnation nonce: scopes ack keys (and the local pin chain) to
        # THIS worker process, so recovery restarts can't alias them.
        import uuid as _uuid
        self._nonce = _uuid.uuid4().hex[:8]

    # -- save ----------------------------------------------------------------

    def save(self, tree: Any, metrics: Optional[Dict[str, Any]] = None,
             shard_spec: Optional[Callable] = None,
             step: Optional[int] = None,
             sync: Optional[bool] = None) -> str:
        """Checkpoint this rank's shards of ``tree``; returns the
        checkpoint directory.  Blocking work: device->host snapshot (+
        queue backpressure).  The checkpoint only becomes ``latest`` once
        the coordinator has every rank's ack and commits the manifest."""
        if step is None:
            step = self._step_seq
        self._step_seq = step + 1
        dirpath = step_dir(self.run_root, step)
        if ckpt_format.is_committed(dirpath):
            # An explicit user step colliding with a committed checkpoint
            # would atomically replace its shard files underneath the
            # manifest — corrupting "latest" with no way to re-commit
            # (the coordinator ignores acks for committed steps).
            raise ckpt_format.CheckpointError(
                f"step {step} is already a committed checkpoint "
                f"({dirpath}); resume PAST a restored checkpoint, never "
                f"over it")
        use_sync = (not self.async_save) if sync is None else sync
        if use_sync and self._writer is not None:
            # A sync save implies every earlier async save of this rank
            # has landed: without the barrier, committing THIS step could
            # let the coordinator's GC reap an older step's directory
            # while the writer is still publishing into it.
            self.flush()

        t0 = time.monotonic()
        snapshot = ckpt_format.snapshot_tree(tree, shard_spec=shard_spec)
        blocking_s = time.monotonic() - t0
        job = WriteJob(dirpath=dirpath, step=step, rank=self.rank,
                       world=self.world_size, snapshot=snapshot,
                       on_done=self._make_on_done(metrics))
        if use_sync:
            t1 = time.monotonic()
            publish_shard(job)
            blocking_s += time.monotonic() - t1
        else:
            blocking_s += self._ensure_writer().submit(job)
        telemetry.observe("ray_tpu_ckpt_save_blocking_seconds", blocking_s)
        # Goodput: only the BLOCKING slice of the save stole step time;
        # the controller reattributes it out of the "step" phase.
        telemetry.note_checkpoint_seconds(blocking_s)
        return dirpath

    def _ensure_writer(self) -> AsyncCheckpointWriter:
        if self._writer is None:
            self._writer = AsyncCheckpointWriter(
                max_inflight=self._max_inflight)
        return self._writer

    def _holder_actor(self):
        if not self.emergency_replica:
            return None
        if not self._holder_resolved:
            self._holder = replica_mod.get_holder(self.experiment)
            self._holder_resolved = True
        return self._holder

    def _make_on_done(self, metrics: Optional[Dict[str, Any]]):
        def on_done(job: WriteJob, index: Dict[str, Any], blob: bytes,
                    write_s: float) -> None:
            replicated = False
            if self.emergency_replica:
                replicated = replica_mod.push_shard(
                    self._holder_actor(), job.step, job.rank, index, blob)
                if self._local_pin is not None:
                    self._local_pin.pin(blob, job.step, index)
            self._ack(job, index, blob, write_s, replicated, metrics)
        return on_done

    def _ack(self, job: WriteJob, index: Dict[str, Any], blob: bytes,
             write_s: float, replicated: bool,
             metrics: Optional[Dict[str, Any]]) -> None:
        from .._private.api import _control
        payload = {
            "step": job.step, "rank": job.rank, "world": job.world,
            "dir": job.dirpath, "nbytes": len(blob),
            "crc32": index["crc32"], "write_s": write_s,
            "replica": replicated, "metrics": dict(metrics or {}),
            "generation": self.generation,
        }
        _control("kv_put",
                 ack_key(self.run_id, job.step, job.rank, self._nonce),
                 pickle.dumps(payload))

    # -- restore -------------------------------------------------------------

    def load(self, path: str,
             placement: Optional[Callable] = None) -> Any:
        """Restore from a committed checkpoint, preferring in-memory
        replica shards over disk when replication is on."""
        t0 = time.monotonic()
        if not ckpt_format.is_committed(path):
            if placement is not None:
                raise ckpt_format.CheckpointError(
                    f"{path} is a legacy single-pickle checkpoint: it "
                    f"has no shard index, so a resharding placement "
                    f"cannot be honored")
            # Legacy pickle layout.
            out = ckpt_format.load_pytree(path)
            return out
        manifest = ckpt_format.read_manifest(path)
        blobs: Dict[int, Any] = {}
        if self.emergency_replica:
            # Memory restore order: same-host pinned blobs first, the
            # peer holder for whatever they miss; disk covers the rest.
            blobs = replica_mod.fetch_local_pins(self.experiment, manifest)
            if len(blobs) < len(manifest["shards"]):
                for rank, shard in replica_mod.fetch_shards(
                        self._holder_actor(), manifest).items():
                    blobs.setdefault(rank, shard)
            blobs = _validated_blobs(blobs, manifest)
        tree = ckpt_format.restore_tree(
            path, placement=placement, blobs=blobs or None)
        source = "replica" if blobs else "disk"
        telemetry.observe("ray_tpu_ckpt_restore_seconds",
                          time.monotonic() - t0, tags={"source": source})
        if blobs:
            telemetry.inc("ray_tpu_ckpt_replica_restores_total")
        return tree

    # -- lifecycle -----------------------------------------------------------

    def flush(self, timeout: Optional[float] = 120.0) -> None:
        if self._writer is None:
            return
        drained = self._writer.wait_idle(timeout)
        self._writer.raise_on_error()
        if not drained:
            # No write ERROR, but the durability guarantee the caller
            # asked for was not met — that must be loud too.
            raise ckpt_format.CheckpointError(
                f"checkpoint writer did not drain within {timeout}s")

    def close(self) -> None:
        try:
            if self._writer is not None:
                self._writer.close()
                self._writer = None
        finally:
            # The pin must be released even when the writer shutdown
            # raises, or the blob stays pinned in host RAM for the rest
            # of the runtime session.
            if self._local_pin is not None:
                self._local_pin.release()
