"""Live diagnostics: process stack capture + postmortem flight recorder.

Two halves of the active-observability story (the passive half — metric
catalog, goodput, timeline — lives in ``util/telemetry.py``):

* **Stack capture** (reference: ``ray stack`` in
  python/ray/scripts/scripts.py, and the py-spy dump the dashboard's hang
  investigation triggers): ``capture_process_stacks`` snapshots
  ``sys._current_frames()`` in the calling process and annotates each
  thread with the task/actor it is executing.  Workers run it on their
  receive thread when a ``StackDumpRequest`` lands, so a worker whose
  executor threads are wedged in user code still answers — which is the
  whole point of the diagnostic.

* **Flight recorder** (reference: the debug-state dumps raylets write on
  SIGTERM plus the GCS task-event history a postmortem pulls):
  ``write_debug_bundle`` collects everything a human attaches to a bug
  report — captured stacks, the task-event tail, the last export-event
  lines, a Prometheus metrics snapshot, and the goodput breakdown — into
  one directory under ``<session>/debug/<timestamp>-<reason>/``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

#: events.jsonl lines / task events captured into a bundle.
EVENT_TAIL_LINES = 200
TASK_EVENT_TAIL = 500
SCHED_DECISION_TAIL = 500


def capture_process_stacks(worker_id: str,
                           actor_id: Optional[str] = None,
                           thread_tasks: Optional[Dict[int, tuple]] = None,
                           is_driver: bool = False) -> Dict[str, Any]:
    """Snapshot every thread's Python stack in THIS process.

    ``thread_tasks`` maps thread idents to ``(task_id_hex, task_name)``
    for threads currently executing a task (maintained by the worker's
    ``_run_task_inner``), so the dump names what each thread is running,
    not just where it is.
    """
    names: Dict[int, tuple] = {}
    for t in threading.enumerate():
        if t.ident is not None:
            names[t.ident] = (t.name, t.daemon)
    threads: List[Dict[str, Any]] = []
    for tid, frame in sys._current_frames().items():
        name, daemon = names.get(tid, ("<unknown>", True))
        task_id, task_name = (thread_tasks or {}).get(tid, (None, None))
        frames = [ln.rstrip("\n")
                  for ln in traceback.format_stack(frame)]
        threads.append({
            "thread_id": tid, "name": name, "daemon": daemon,
            "task_id": task_id, "task_name": task_name,
            "frames": frames,
        })
    threads.sort(key=lambda t: (t["daemon"], t["name"]))
    return {
        "worker_id": worker_id,
        "pid": os.getpid(),
        "is_driver": is_driver,
        "actor_id": actor_id,
        "time": time.time(),
        "threads": threads,
    }


def format_stack_dump(dump: Dict[str, Any]) -> str:
    """Human-readable rendering of a ``ctl_stack_dump`` result (the
    ``ray-tpu stack`` CLI output)."""
    lines: List[str] = [f"=== cluster stack dump @ {dump.get('time')} ==="]
    for rec in dump.get("stacks", ()):
        who = "driver" if rec.get("is_driver") else f"worker {rec['worker_id'][:12]}"
        head = f"--- {who} pid={rec.get('pid')}"
        if rec.get("actor_id"):
            head += f" actor={rec['actor_id'][:12]}"
        if rec.get("node_id"):
            head += f" node={rec['node_id'][:12]}"
        lines.append(head + " ---")
        for th in rec.get("threads", ()):
            tag = f"thread {th['name']} (id={th['thread_id']})"
            if th.get("task_name"):
                tag += f" running task {th['task_name']} [{th['task_id']}]"
            lines.append(tag)
            lines.extend("  " + f for f in th.get("frames", ()))
    missing = dump.get("unresponsive") or ()
    if missing:
        lines.append(f"unresponsive workers (no reply in time): "
                     f"{', '.join(w[:12] for w in missing)}")
    return "\n".join(lines)


def _slug(reason: str, maxlen: int = 48) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
    return out[:maxlen] or "dump"


def write_debug_bundle(rt, reason: str,
                       stacks: Optional[Dict[str, Any]] = None,
                       capture_stacks: bool = True,
                       stack_timeout_s: float = 2.0,
                       extra: Optional[Dict[str, Any]] = None,
                       profile_s: Optional[float] = None) -> str:
    """Write a postmortem bundle for the given driver Runtime; returns the
    bundle directory path.  Every section is best-effort: a broken
    subsystem must never stop the remaining forensics from landing.

    ``profile_s`` > 0 attaches an on-demand cluster profile
    (``profile_trace.json`` — the same merged Chrome trace ``ray-tpu
    profile`` produces) so a watchdog-trip bundle carries WHERE the time
    was going, not just where the threads were stuck.  None defers to
    the ``debug_bundle_profile_s`` config (default off: a profile holds
    the bundle open for its whole capture window)."""
    ts = time.strftime("%Y%m%d-%H%M%S")
    frac = int((time.time() % 1) * 1e6)
    path = os.path.join(rt.session_dir, "debug",
                        f"{ts}-{frac:06d}-{_slug(reason)}")
    os.makedirs(path, exist_ok=True)
    contents: List[str] = []

    def section(fname: str, produce) -> None:
        from . import sanitizer
        try:
            data = produce()
            if data is None:
                return
            # tracked_open: bundle handles register with the leak
            # sanitizer while open, so a wedged producer shows up in the
            # shutdown diff with this site.
            with sanitizer.tracked_open(os.path.join(path, fname),
                                        "w") as f:
                f.write(data)
            contents.append(fname)
        except Exception:  # noqa: BLE001 — forensics are best-effort
            pass

    if stacks is None and capture_stacks:
        try:
            stacks = rt.ctl_stack_dump(timeout_s=stack_timeout_s)
        except Exception:  # noqa: BLE001
            stacks = None
    if stacks is not None:
        section("stacks.json",
                lambda: json.dumps(stacks, indent=1, default=str))
    section("task_events.json", lambda: json.dumps(
        rt.events.snapshot(limit=TASK_EVENT_TAIL), indent=1, default=str))
    section("events_tail.jsonl", lambda: "\n".join(
        rt.log_monitor.tail("events.jsonl", EVENT_TAIL_LINES)) + "\n")

    def _metrics():
        from ray_tpu.util.metrics import prometheus_text
        return prometheus_text()
    section("metrics.prom", _metrics)

    def _goodput():
        from ray_tpu.util.telemetry import goodput_summary
        g = goodput_summary()
        return json.dumps(g, indent=1) if g is not None else None
    section("goodput.json", _goodput)

    def _sched():
        # Scheduler decision ring + queue depths: a hang bundle should
        # say WHY the pending tasks are pending, not just that they are.
        sched = getattr(rt, "scheduler", None)
        if sched is None or not hasattr(sched, "ring"):
            return None
        return json.dumps({
            "stats": sched.ring.stats(),
            "queues": sched.queue_depths(),
            "decisions": sched.ring.snapshot(limit=SCHED_DECISION_TAIL),
        }, indent=1, default=str)
    section("sched_decisions.json", _sched)

    def _objects():
        # Data-plane counterpart of _sched: where the memory went.  A
        # postmortem bundle should attribute occupancy (per node, top
        # objects, leak candidates) and carry the store event-ring tail
        # so spill/pull storms around the crash are reconstructable.
        if not hasattr(rt, "ctl_memory_summary"):
            return None
        return json.dumps({
            "memory": rt.ctl_memory_summary(),
            "store_events": rt.ctl_store_events(limit=500),
        }, indent=1, default=str)
    section("objects.json", _objects)

    def _locks():
        # Lock-order detector findings (RAY_TPU_DEBUG_LOCKS=1): written
        # whenever the detector is active or has recorded anything, so a
        # deadlock bundle carries the acquisition-order story.
        from ray_tpu.devtools import lockdebug
        rep = lockdebug.report()
        if not rep["installed"] and not rep["findings"]:
            return None
        return json.dumps(rep, indent=1, default=str)
    section("lock_findings.json", _locks)

    def _lock_contention():
        # Contention profiler snapshot (RAY_TPU_LOCK_PROFILE=1 or
        # RAY_TPU_DEBUG_LOCKS=1): per-site wait/hold histograms, so a
        # slow-control-plane bundle names its hottest lock.  Render
        # with `ray-tpu lint --lock-report <file>`.
        from ray_tpu.devtools import lockdebug
        rep = lockdebug.contention_report()
        if not rep["installed"] and not rep["sites"]:
            return None
        return json.dumps(rep, indent=1, default=str)
    section("lock_contention.json", _lock_contention)

    def _syncs():
        # Host-sync tripwire snapshot (RAY_TPU_SYNC_DEBUG=1): per-site
        # implicit device->host sync counts and blocked-time histograms,
        # so a slow-step bundle names the line stalling on the device.
        # Render with `ray-tpu lint --sync-report <file>`.
        from ray_tpu.devtools import syncdebug
        rep = syncdebug.report()
        if not rep["installed"] and not rep["sites"]:
            return None
        return json.dumps(rep, indent=1, default=str)
    section("sync_findings.json", _syncs)

    def _profile():
        # On-demand cluster profile for the incident window (opt-in:
        # the capture blocks for its duration).
        from .config import Config
        dur = Config.get("debug_bundle_profile_s") \
            if profile_s is None else profile_s
        if not dur or dur <= 0:
            return None
        out = rt.ctl_profile(duration_s=dur, save=False)
        return json.dumps(out["trace"], default=str)
    section("profile_trace.json", _profile)

    def _alerts():
        # SLO alert states + recent transitions: a bundle written because
        # something went wrong should say which objectives were burning.
        view = getattr(rt, "metricsview", None)
        if view is None:
            return None
        return json.dumps(view.alerts(recent=100), indent=1, default=str)
    section("alerts.json", _alerts)

    def _history():
        # Recent time-series history (bounded per-series tail) so the
        # bundle carries the minutes BEFORE the incident, not just the
        # instant of it (metrics.prom is only the final cumulative state).
        view = getattr(rt, "metricsview", None)
        if view is None:
            return None
        return json.dumps(view.bundle_snapshot(), indent=1, default=str)
    section("metrics_history.json", _history)

    def _leaks():
        # Leak-sanitizer registries (RAY_TPU_SANITIZE=1): the live
        # framework threads / pins / tracked handles / named actors with
        # creation sites — a hang/death bundle shows what was held.
        from ray_tpu._private import sanitizer
        if not sanitizer.is_enabled():
            return None
        return json.dumps(sanitizer.report(), indent=1, default=str)
    section("leak_findings.json", _leaks)

    section("manifest.json", lambda: json.dumps({
        "reason": reason,
        "time": time.time(),
        "session_dir": rt.session_dir,
        "extra": extra or {},
        "contents": sorted(contents),
    }, indent=1, default=str))
    return path
