"""Connector pipelines: composable obs/action transforms on the rollout
path.

Reference: rllib/connectors/ (ConnectorV2 pipelines between env and module
— env-to-module transforms observations before inference, module-to-env
transforms actions before stepping).  Connectors carry state (e.g. running
mean/std) that must ship with policy weights so remote runners and the
learner see the same preprocessing — state here is a plain dict so it
rides the same sync path as params.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Connector:
    """One transform stage.  ``__call__(batch) -> batch`` where batch is a
    [N, ...] numpy array of observations (env-to-module) or actions
    (module-to-env)."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, batch: np.ndarray) -> np.ndarray:
        """Apply without mutating connector state (for off-path uses like
        truncation bootstraps and evaluation).  Stateless connectors just
        delegate to __call__."""
        return self(batch)

    def on_episode_boundaries(self, done_mask: np.ndarray) -> None:
        """Notify per-sub-env episode resets BEFORE the next __call__ (whose
        batch holds the new episodes' reset observations at masked rows).
        History-keeping connectors clear those rows."""

    # Stateful connectors override these so their state syncs across
    # runners with the weights (reference: connector state in checkpoints).
    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    def merge_states(self, states: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Combine per-runner states into one canonical state (reference:
        rllib's distributed MeanStdFilter aggregation).  Default: stateless
        — nothing to merge."""
        return {}


class ConnectorPipeline(Connector):
    """Ordered list of connectors applied left-to-right (reference:
    ConnectorPipelineV2)."""

    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            batch = c(batch)
        return batch

    def transform(self, batch: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            batch = c.transform(batch)
        return batch

    def on_episode_boundaries(self, done_mask: np.ndarray) -> None:
        for c in self.connectors:
            c.on_episode_boundaries(done_mask)

    def get_state(self) -> Dict[str, Any]:
        return {str(i): c.get_state()
                for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])

    def merge_states(self, states: List[Dict[str, Any]]
                     ) -> Dict[str, Any]:
        return {str(i): c.merge_states([s.get(str(i), {}) for s in states])
                for i, c in enumerate(self.connectors)}

    @property
    def output_dim_factor(self) -> int:
        """How the pipeline scales the observation dim (frame-stacking
        multiplies it)."""
        f = 1
        for c in self.connectors:
            f *= getattr(c, "dim_factor", 1)
        return f


class MeanStdFilter(Connector):
    """Running mean/std observation normalization (reference: rllib's
    MeanStdFilter connector + its distributed synchronization).

    State is split into a *base* aggregate (the cluster-wide stats as of
    the last sync) and a local *delta* (samples seen since).  Sync
    protocol: the group gathers every runner's delta, merges them into the
    shared base, and broadcasts the new base back — which resets deltas.
    Merging absolute states instead would re-count the base once per
    runner per sync (n ~ runners^iterations) and freeze the stats on
    early data.  Aggregates are (n, mean, m2) Chan et al. triples with
    O(1) merges.
    """

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self._base: Optional[tuple] = None   # (n, mean, m2) at last sync
        self._delta: Optional[tuple] = None  # local since last sync

    @staticmethod
    def _merge_agg(a: Optional[tuple], b: Optional[tuple]
                   ) -> Optional[tuple]:
        if a is None or a[0] == 0:
            return b
        if b is None or b[0] == 0:
            return a
        na, mean_a, m2_a = a
        nb, mean_b, m2_b = b
        n = na + nb
        d = mean_b - mean_a
        mean = mean_a + d * (nb / n)
        m2 = m2_a + m2_b + d ** 2 * (na * nb / n)
        return (n, mean, m2)

    def _combined(self) -> Optional[tuple]:
        return self._merge_agg(self._base, self._delta)

    @property
    def count(self) -> int:
        agg = self._combined()
        return 0 if agg is None else int(agg[0])

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        batch = np.asarray(batch, np.float32)
        if self.update:
            rows = batch.reshape(-1, batch.shape[-1]).astype(np.float64)
            if len(rows):
                b_mean = rows.mean(axis=0)
                b_m2 = ((rows - b_mean) ** 2).sum(axis=0)
                self._delta = self._merge_agg(
                    self._delta, (len(rows), b_mean, b_m2))
        return self._normalize(batch)

    def transform(self, batch: np.ndarray) -> np.ndarray:
        return self._normalize(np.asarray(batch, np.float32))

    def _normalize(self, batch: np.ndarray) -> np.ndarray:
        agg = self._combined()
        if agg is None or agg[0] < 2:
            return np.clip(batch, -self.clip, self.clip)
        n, mean, m2 = agg
        std = np.sqrt(m2 / (n - 1)) + 1e-8
        out = (batch - mean.astype(np.float32)) / std.astype(np.float32)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self) -> Dict[str, Any]:
        return {"base": self._base, "delta": self._delta}

    def set_state(self, state: Dict[str, Any]) -> None:
        """Install a state verbatim.  Sync broadcasts carry merged states
        with ``delta=None``, so installing one resets the local delta —
        its samples are already inside the merged base."""
        self._base = state.get("base")
        self._delta = state.get("delta")

    def merge_states(self, states: List[Dict[str, Any]]) -> Dict[str, Any]:
        # Every runner shares the same base after a sync; fold each
        # runner's delta in exactly once.
        base = None
        for s in states:
            if s and s.get("base") is not None:
                base = s["base"]
                break
        for s in states:
            if s:
                base = self._merge_agg(base, s.get("delta"))
        return {"base": base, "delta": None}


class FrameStack(Connector):
    """Stack the last k observations per sub-env along the feature axis
    (reference: rllib FrameStackingEnvToModule).  Expects a fixed batch
    (one row per sub-env) each call; reset() clears history."""

    def __init__(self, k: int = 4):
        self.k = k
        self.dim_factor = k
        self._frames: Optional[deque] = None
        self._reset_mask: Optional[np.ndarray] = None

    def reset(self) -> None:
        self._frames = None
        self._reset_mask = None

    def on_episode_boundaries(self, done_mask: np.ndarray) -> None:
        # Applied at the next __call__, whose batch carries the new
        # episodes' reset observations at the masked rows — the old
        # episode's frames must not leak into the new episode's stack.
        self._reset_mask = np.asarray(done_mask, bool).copy()

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        batch = np.array(batch, np.float32)  # own copy: frames are mutated
        if self._frames is None or self._frames[0].shape != batch.shape:
            self._frames = deque([batch] * self.k, maxlen=self.k)
        else:
            self._frames.append(batch)
            if self._reset_mask is not None and self._reset_mask.any():
                m = self._reset_mask
                for f in self._frames:
                    f[m] = batch[m]
        self._reset_mask = None
        return np.concatenate(list(self._frames), axis=-1)

    def transform(self, batch: np.ndarray) -> np.ndarray:
        batch = np.asarray(batch, np.float32)
        if self._frames is None or self._frames[0].shape != batch.shape:
            return np.concatenate([batch] * self.k, axis=-1)
        frames = list(self._frames)[1:] + [batch]
        return np.concatenate(frames, axis=-1)


class LambdaConnector(Connector):
    """Wrap a stateless function (reference: custom ConnectorV2 one-offs)."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray]):
        self.fn = fn

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        return self.fn(batch)


class ClipActions(Connector):
    """Clip continuous actions into the env's bounds (module-to-env,
    reference: rllib's clip_actions config)."""

    def __init__(self, low: float, high: float):
        self.low = low
        self.high = high

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        return np.clip(batch, self.low, self.high)


class RewardClip(Connector):
    """Clip (or sign-compress) rewards before learning — the standard
    Atari-style stabilizer (reference: rllib clip_rewards config: True ->
    sign, float -> symmetric clip)."""

    def __init__(self, bound: float = 1.0, sign: bool = False):
        self.bound = bound
        self.sign = sign

    def __call__(self, rewards: np.ndarray) -> np.ndarray:
        r = np.asarray(rewards)
        if self.sign:
            return np.sign(r)
        return np.clip(r, -self.bound, self.bound)


class ObsFlatten(Connector):
    """Flatten structured observations to 1-D feature vectors
    (env-to-module; reference: rllib's flatten_observations preprocessor)."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        b = np.asarray(batch)
        return b.reshape(b.shape[0], -1) if b.ndim > 1 else b
