"""Dynamic request batching (reference: python/ray/serve/batching.py
@serve.batch — accumulate calls until max_batch_size or timeout, run the
wrapped method once on the list, scatter results)."""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._items: List[Any] = []
        self._events: List[threading.Event] = []
        self._enqueued: List[float] = []  # perf_counter at submit
        self._results: List[Any] = []
        self._flush_timer: threading.Timer = None  # type: ignore

    def submit(self, instance, item):
        ev = threading.Event()
        with self._lock:
            self._items.append(item)
            self._events.append(ev)
            self._enqueued.append(time.perf_counter())
            idx = len(self._items) - 1
            if len(self._items) >= self.max_batch_size:
                batch, events, enq = self._take()
                self._run(instance, batch, events, enq)
            elif self._flush_timer is None:
                t = threading.Timer(
                    self.timeout, self._flush_due, args=(instance,))
                t.daemon = True
                self._flush_timer = t
                t.start()
        ev.wait()
        return ev.result  # type: ignore[attr-defined]

    def _take(self):
        batch, self._items = self._items, []
        events, self._events = self._events, []
        enq, self._enqueued = self._enqueued, []
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        return batch, events, enq

    def _flush_due(self, instance):
        with self._lock:
            if not self._items:
                self._flush_timer = None
                return
            batch, events, enq = self._take()
        self._run_outside(instance, batch, events, enq)

    def _run(self, instance, batch, events, enq):
        # Called with lock held for the size-trigger path; do the work
        # outside the lock.
        from .._private import sanitizer
        sanitizer.spawn(self._run_outside,
                        args=(instance, batch, events, enq),
                        name="serve-batch")

    def _note_batch(self, batch, enq) -> None:
        try:
            from ..util import telemetry
        except Exception:
            return
        tags = {"method": getattr(self.fn, "__name__", "batch")}
        now = time.perf_counter()
        for t in enq:
            telemetry.observe("ray_tpu_serve_queue_wait_seconds",
                              max(0.0, now - t), tags=tags)
        telemetry.observe("ray_tpu_serve_batch_size", len(batch),
                          tags=tags)

    def _run_outside(self, instance, batch, events, enq):
        self._note_batch(batch, enq)
        try:
            outs = (self.fn(instance, batch) if instance is not None
                    else self.fn(batch))
            if len(outs) != len(batch):
                raise ValueError(
                    f"batched fn returned {len(outs)} results for "
                    f"{len(batch)} inputs")
        except Exception as e:  # noqa: BLE001
            outs = [e] * len(batch)
        for ev, out in zip(events, outs):
            ev.result = out  # type: ignore[attr-defined]
            ev.set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: methods receive List[item] instead of item.

    The batcher (which holds locks/timers) is created lazily per replica
    process so decorated classes stay picklable.
    """
    def wrap(fn):
        attr = f"_ray_tpu_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def method(self, item):
            batcher = getattr(self, attr, None)
            if batcher is None:
                batcher = _Batcher(fn, max_batch_size, batch_wait_timeout_s)
                try:
                    setattr(self, attr, batcher)
                except AttributeError:
                    pass
            out = batcher.submit(self, item)
            if isinstance(out, Exception):
                raise out
            return out
        return method
    if _fn is not None:
        return wrap(_fn)
    return wrap
