"""Serve tests (reference pattern: python/ray/serve/tests)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=2)
class Doubler:
    def __call__(self, payload):
        if isinstance(payload, dict):
            return {"doubled": payload.get("x", 0) * 2}
        return payload * 2

    def describe(self):
        import os
        return os.getpid()


class TestServeCore:
    def test_deploy_and_call(self, ray_start):
        handle = serve.run(Doubler.bind())
        out = ray_tpu.get(handle.remote(21), timeout=60)
        assert out == 42
        serve.shutdown()

    def test_two_replicas_distinct_processes(self, ray_start):
        handle = serve.run(Doubler.bind())
        pids = set()
        for _ in range(20):
            pids.add(ray_tpu.get(handle.describe.remote(), timeout=60))
        assert len(pids) == 2
        serve.shutdown()

    def test_function_deployment(self, ray_start):
        @serve.deployment
        def greeter(payload):
            return f"hello {payload}"
        handle = serve.run(greeter.bind())
        assert ray_tpu.get(handle.remote("tpu"), timeout=60) == "hello tpu"
        serve.shutdown()

    def test_redeploy_replaces(self, ray_start):
        h1 = serve.run(Doubler.bind())
        ray_tpu.get(h1.remote(1), timeout=60)
        h2 = serve.run(Doubler.options(num_replicas=1).bind())
        assert ray_tpu.get(h2.remote(2), timeout=60) == 4
        assert serve.status()["Doubler"]["num_replicas"] == 1
        serve.shutdown()

    def test_init_args(self, ray_start):
        @serve.deployment
        class Scaler:
            def __init__(self, k):
                self.k = k

            def __call__(self, payload):
                return payload * self.k
        handle = serve.run(Scaler.bind(10))
        assert ray_tpu.get(handle.remote(4), timeout=60) == 40
        serve.shutdown()

    def test_http_ingress(self, ray_start):
        import json
        import urllib.request
        handle = serve.run(Doubler.bind(), http_port=18123)
        req = urllib.request.Request(
            "http://127.0.0.1:18123/Doubler",
            data=json.dumps({"x": 5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            body = json.loads(resp.read())
        assert body["result"] == {"doubled": 10}
        serve.shutdown()


class TestAdmissionBound:
    def test_max_queued_requests_sheds(self, ray_start):
        """Past replica capacity + the queue allowance, handle.remote
        raises a retriable OverloadError instead of queueing unboundedly
        (SLO-aware admission on the handle path)."""
        @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                          max_queued_requests=1)
        class Slow:
            def __call__(self, payload):
                time.sleep(0.5)
                return payload

        handle = serve.run(Slow.bind())
        # Warm the path (replica up, router snapshot fetched).
        ray_tpu.get(handle.remote(0), timeout=60)
        refs = []
        shed = 0
        for i in range(8):
            try:
                refs.append(handle.remote(i))
            except serve.OverloadError as e:
                assert e.retriable
                shed += 1
        assert shed > 0, "burst past capacity+queue must shed"
        assert refs, "requests within the bound are still admitted"
        for r in refs:
            ray_tpu.get(r, timeout=60)
        # Drained: admission accepts again.
        deadline = time.monotonic() + 30
        while True:
            try:
                assert ray_tpu.get(handle.remote(7), timeout=60) == 7
                break
            except serve.OverloadError:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        serve.shutdown()


class TestBatching:
    def test_batch_accumulates(self, ray_start):
        @serve.deployment
        class BatchAdder:
            @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
            def __call__(self, items):
                # Whole batch processed at once.
                return [i + 100 for i in items]

        handle = serve.run(BatchAdder.bind())
        refs = [handle.remote(i) for i in range(8)]
        out = sorted(ray_tpu.get(refs, timeout=60))
        assert out == [100 + i for i in range(8)]
        serve.shutdown()


class TestMultiplex:
    def test_lru_cache_and_eviction(self, ray_start):
        @serve.deployment(num_replicas=1)
        class MultiModel:
            def __init__(self):
                self.loads = []

            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id):
                self.loads.append(model_id)
                return f"model:{model_id}"

            def __call__(self, x):
                model = self.get_model()
                return {"model": model, "loads": list(self.loads),
                        "resident": self.get_model.loaded_model_ids}

        handle = serve.run(MultiModel.bind())

        def ask(mid):
            return ray_tpu.get(
                handle.options(multiplexed_model_id=mid).remote(0),
                timeout=60)

        r1 = ask("m1")
        assert r1["model"] == "model:m1" and r1["loads"] == ["m1"]
        ask("m2")
        r3 = ask("m1")          # cached — no reload
        assert r3["loads"] == ["m1", "m2"]
        r4 = ask("m3")          # evicts m2 (LRU)
        assert r4["loads"] == ["m1", "m2", "m3"]
        assert sorted(r4["resident"]) == ["m1", "m3"]
        r5 = ask("m2")          # m2 was evicted: reloaded
        assert r5["loads"] == ["m1", "m2", "m3", "m2"]
        serve.shutdown()

    def test_router_model_affinity(self, ray_start):
        @serve.deployment(num_replicas=2)
        class PidModel:
            @serve.multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id):
                return model_id

            def __call__(self, x):
                import os
                self.get_model()
                return os.getpid()

        handle = serve.run(PidModel.bind())
        h = handle.options(multiplexed_model_id="alpha")
        pids = [ray_tpu.get(h.remote(i), timeout=60) for i in range(6)]
        # After the first request establishes affinity, every later
        # request for the same model lands on the same replica.
        assert len(set(pids[1:])) == 1
        serve.shutdown()

    def test_model_id_outside_request_is_none(self, ray_start):
        from ray_tpu.serve import get_multiplexed_model_id
        assert get_multiplexed_model_id() is None


class TestServeControlPlane:
    """Reconciliation + autoscaling (reference:
    serve/_private/deployment_state.py:2795 reconcile loops,
    serve/autoscaling_policy.py)."""

    def test_dead_replica_recreated(self, ray_start):
        from ray_tpu import serve

        @serve.deployment(num_replicas=2)
        class Svc:
            def __call__(self, x):
                return x * 2

            def pid(self):
                import os
                return os.getpid()

        h = serve.run(Svc.bind())
        assert ray_tpu.get(h.remote(21), timeout=30) == 42
        # Kill one replica out from under the controller actor.
        ctrl = serve.api._existing_controller()
        snapshot = ray_tpu.get(ctrl.replica_snapshot.remote("Svc"),
                               timeout=30)
        assert len(snapshot) == 2
        victim_hex = snapshot[0][0]
        from ray_tpu._private.api import ActorHandle
        from ray_tpu._private.ids import ActorID
        ray_tpu.kill(ActorHandle(ActorID(bytes.fromhex(victim_hex)), "Svc"))
        # Controller notices the death and backfills to target (generous
        # deadline: replica spawn = interpreter boot, slow on a loaded
        # single-core CI host).
        deadline = time.time() + 90
        while time.time() < deadline:
            snap = ray_tpu.get(ctrl.replica_snapshot.remote("Svc"),
                               timeout=30)
            ids = [e[0] for e in snap]
            if victim_hex not in ids and len(ids) == 2:
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"controller never backfilled: {ids}")
        # Requests still served after self-heal (router converges from
        # the published snapshot; only in-flight requests may have erred).
        assert ray_tpu.get(h.remote(5), timeout=60) == 10
        serve.shutdown()

    def test_autoscale_up_and_down(self, ray_start):
        from ray_tpu import serve
        from ray_tpu.serve import AutoscalingConfig

        @serve.deployment(
            num_replicas=1, max_ongoing_requests=4,
            autoscaling_config=AutoscalingConfig(
                min_replicas=1, max_replicas=3,
                target_ongoing_requests=1.0,
                upscale_delay_s=0.3, downscale_delay_s=0.6))
        class Slow:
            def __call__(self, t):
                time.sleep(t)
                return "done"

        h = serve.run(Slow.bind())

        def n_replicas():
            return serve.status()["Slow"]["num_replicas"]

        # Load ramp: many slow concurrent requests -> queue depth >> target
        # (the router pushes its in-flight totals to the controller).
        refs = [h.remote(3.0) for _ in range(9)]
        deadline = time.time() + 40
        while time.time() < deadline:
            if n_replicas() >= 3:
                break
            time.sleep(0.1)
        assert n_replicas() >= 3, "did not scale up"
        ray_tpu.get(refs, timeout=120)
        # Idle: scales back down to min.
        deadline = time.time() + 40
        while time.time() < deadline:
            if n_replicas() == 1:
                break
            time.sleep(0.1)
        assert n_replicas() == 1, "did not scale down"
        serve.shutdown()

    def test_replica_set_push_on_change(self, ray_start):
        """Replica-set snapshots version-bump in the cluster KV when the
        reconciler changes the set (reference: LongPollHost pushes)."""
        import pickle

        from ray_tpu import serve
        from ray_tpu._private.api import ActorHandle, _control
        from ray_tpu._private.ids import ActorID
        from ray_tpu.serve.controller import REPLICA_KV_PREFIX

        @serve.deployment(num_replicas=1)
        class P:
            def __call__(self, x):
                return x

        serve.run(P.bind())
        v0, entries = pickle.loads(_control("kv_get",
                                            REPLICA_KV_PREFIX + "P"))[:2]
        assert len(entries) == 1
        # Kill the only replica; the reconciler publishes a new snapshot.
        ray_tpu.kill(ActorHandle(ActorID(bytes.fromhex(entries[0][0])), "P"))
        deadline = time.time() + 30
        while time.time() < deadline:
            v1, e1 = pickle.loads(_control("kv_get",
                                           REPLICA_KV_PREFIX + "P"))[:2]
            if v1 > v0 and e1 and e1[0][0] != entries[0][0]:
                break
            time.sleep(0.1)
        else:
            pytest.fail("snapshot never re-published after replica death")
        serve.shutdown()


class TestNodeProxies:
    def test_http_and_grpc_proxy_ingress(self, ray_start):
        """Per-node proxy actors serve HTTP + proto-free gRPC ingress
        (reference: serve/_private/proxy.py:601,1084,1633 — one proxy
        actor per node)."""
        import json
        import urllib.request

        from ray_tpu.serve import proxy

        serve.run(Doubler.bind())
        try:
            addrs = proxy.start_node_proxies()
            assert len(addrs) == 1  # single-node cluster: one proxy
            ports = next(iter(addrs.values()))
            assert ports["http_port"] and ports["grpc_port"]

            # HTTP ingress through the proxy actor.
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports['http_port']}/Doubler",
                data=json.dumps({"x": 21}).encode(),
                headers={"Content-Type": "application/json"})
            body = json.loads(urllib.request.urlopen(
                req, timeout=60).read())
            assert body["result"] == {"doubled": 42}

            # gRPC ingress: generic bytes method, JSON payloads.
            import grpc
            chan = grpc.insecure_channel(
                f"127.0.0.1:{ports['grpc_port']}")
            call = chan.unary_unary("/ray_tpu.serve/Doubler")
            resp = json.loads(call(json.dumps({"x": 5}).encode(),
                                   timeout=60))
            assert resp["result"] == {"doubled": 10}

            # Idempotent restart returns the same live proxies.
            again = proxy.start_node_proxies()
            assert again.keys() == addrs.keys()
        finally:
            proxy.stop_node_proxies()
            serve.shutdown()

    def test_typed_proto_grpc_ingress(self, ray_start, tmp_path):
        """A user-supplied compiled proto served as REAL typed gRPC
        through the per-node proxies (reference: gRPCProxy with
        grpc_servicer_functions, serve/_private/proxy.py:601): a stock
        gRPC client using FromString/SerializeToString of the generated
        classes calls a deployment end-to-end."""
        import shutil
        import subprocess
        import sys

        if shutil.which("protoc") is None:
            pytest.skip("protoc not available")
        proto_dir = str(tmp_path / "protos")
        import os
        os.makedirs(proto_dir)
        with open(os.path.join(proto_dir, "rt_echo.proto"), "w") as f:
            f.write(
                'syntax = "proto3";\n'
                "package rtdemo;\n"
                "message EchoRequest { string text = 1; int32 times = 2; }\n"
                "message EchoReply { string text = 1; int32 length = 2; }\n")
        subprocess.run(["protoc", f"--python_out={proto_dir}",
                        "-I", proto_dir, "rt_echo.proto"], check=True)
        sys.path.insert(0, proto_dir)  # ships to workers via sys.path
        try:
            import rt_echo_pb2 as pb

            from ray_tpu.serve import proxy

            @serve.deployment(name="Echoer")
            class Echoer:
                def __call__(self, req):
                    text = req.text * req.times
                    return {"text": text, "length": len(text)}

            serve.run(Echoer.bind())
            serve.add_grpc_service("rtdemo.EchoService", {
                "Echo": serve.GrpcMethod(
                    deployment="Echoer",
                    request_type=pb.EchoRequest,
                    response_type=pb.EchoReply),
            })
            addrs = proxy.start_node_proxies()
            port = next(iter(addrs.values()))["grpc_port"]

            import grpc
            chan = grpc.insecure_channel(f"127.0.0.1:{port}")
            call = chan.unary_unary(
                "/rtdemo.EchoService/Echo",
                request_serializer=pb.EchoRequest.SerializeToString,
                response_deserializer=pb.EchoReply.FromString)
            reply = call(pb.EchoRequest(text="ab", times=3), timeout=60)
            assert isinstance(reply, pb.EchoReply)
            assert reply.text == "ababab" and reply.length == 6

            # Unregistered methods still 404 (UNIMPLEMENTED).
            bad = chan.unary_unary(
                "/rtdemo.EchoService/Nope",
                request_serializer=pb.EchoRequest.SerializeToString,
                response_deserializer=pb.EchoReply.FromString)
            with pytest.raises(grpc.RpcError) as ei:
                bad(pb.EchoRequest(text="x"), timeout=30)
            assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
            serve.remove_grpc_service("rtdemo.EchoService")
        finally:
            sys.path.remove(proto_dir)
            from ray_tpu.serve import proxy as _p
            _p.stop_node_proxies()
            serve.shutdown()
