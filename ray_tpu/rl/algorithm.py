"""Algorithm + AlgorithmConfig: the RL training driver.

Reference: rllib/algorithms/algorithm.py:208 (Algorithm is a Trainable with
``step:1169`` orchestrating ``training_step:2420``) and
algorithm_config.py (builder-style AlgorithmConfig: .environment(),
.env_runners(), .training(), .learners(), .build_algo()).
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Type

from .env import make_env
from .env_runner import EnvRunnerGroup
from .rl_module import RLModuleSpec


class AlgorithmConfig:
    """Builder for algorithm hyperparameters (fluent API like the
    reference: config.environment("CartPole-v1").training(lr=1e-3))."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env_spec: Any = None
        self.num_env_runners = 0
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 128
        # Factory returning a list of env-to-module connectors (reference:
        # AlgorithmConfig.env_runners(env_to_module_connector=...)); a
        # factory (not an instance) so every runner gets its own state.
        self.env_to_module_fn: Optional[Callable] = None
        self.num_learners = 0
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 512
        self.seed = 0
        self.module_hidden = (64, 64)
        # Custom module factory (see rl_module(module_factory=...)).
        self.module_factory: Optional[Callable] = None
        self.extra: Dict[str, Any] = {}

    # -- fluent setters --------------------------------------------------- #

    def environment(self, env: Any) -> "AlgorithmConfig":
        self.env_spec = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_to_module_connector: Optional[Callable] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_fn = env_to_module_connector
        return self

    def build_env_to_module(self):
        """Instantiate the connector pipeline (fresh state per runner)."""
        if self.env_to_module_fn is None:
            return None
        from .connectors import ConnectorPipeline
        made = self.env_to_module_fn()
        if isinstance(made, ConnectorPipeline):
            return made
        return ConnectorPipeline(list(made) if isinstance(made, (list, tuple))
                                 else [made])

    def learners(self, *, num_learners: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 train_batch_size: Optional[int] = None,
                 **extra: Any) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        self.extra.update(extra)
        return self

    def rl_module(self, *, hidden=None,
                  module_factory=None) -> "AlgorithmConfig":
        """``module_factory``: zero-arg callable returning a custom
        module (models.CNNPolicyModule / GRUPolicyModule, or anything
        with the module dict surface).  Env runners AND learners build
        from it, so recurrent modules train end-to-end (reference:
        rl_module(rl_module_spec=...) custom RLModule classes)."""
        if hidden is not None:
            self.module_hidden = tuple(hidden)
        if module_factory is not None:
            self.module_factory = module_factory
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    # -- build ------------------------------------------------------------ #

    def module_spec(self) -> RLModuleSpec:
        probe = make_env(self.env_spec)
        obs_dim = probe.observation_dim
        if self.env_to_module_fn is not None:
            obs_dim *= self.build_env_to_module().output_dim_factor
        return RLModuleSpec(obs_dim, probe.num_actions,
                            tuple(self.module_hidden))

    def build_algo(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc.")
        return self.algo_class(self)

    # legacy alias (reference keeps .build around)
    build = build_algo


class Algorithm:
    """Iterative trainer; subclass implements ``training_step``."""

    # Off-policy algorithms that drive their own env loop (DQN) set this
    # False to skip building the policy-rollout EnvRunnerGroup.
    _use_env_runner_group = True

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._start = time.monotonic()  # duration base: NTP-immune
        self.env_runner_group: Optional[EnvRunnerGroup] = None
        if self._use_env_runner_group:
            self.env_runner_group = EnvRunnerGroup(
                lambda: make_env(config.env_spec),
                num_env_runners=config.num_env_runners,
                num_envs_per_runner=config.num_envs_per_runner,
                module_spec=config.module_spec(), seed=config.seed,
                env_to_module_fn=config.env_to_module_fn
                and config.build_env_to_module,
                module_fn=config.module_factory)
        self.setup(config)

    # -- subclass hooks ---------------------------------------------------- #

    def setup(self, config: AlgorithmConfig) -> None:
        pass

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- public API --------------------------------------------------------- #

    def train(self) -> Dict[str, Any]:
        """One iteration (reference: Algorithm.step:1169)."""
        t0 = time.monotonic()
        results = self.training_step()
        self.iteration += 1
        if self.env_runner_group is not None:
            results.setdefault("env_runners",
                               self.env_runner_group.aggregate_metrics())
        results["training_iteration"] = self.iteration
        results["time_this_iter_s"] = time.monotonic() - t0
        results["time_total_s"] = time.monotonic() - self._start
        return results

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, params) -> None:
        raise NotImplementedError

    def save(self, checkpoint_dir: str) -> str:
        """Reference: Checkpointable.save_to_path (rllib/utils/checkpoints)."""
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump({"weights": self.get_weights(),
                         "iteration": self.iteration}, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.set_weights(state["weights"])
        self.iteration = state["iteration"]
        if self.env_runner_group is not None:
            self.env_runner_group.sync_weights(state["weights"])

    def stop(self) -> None:
        if self.env_runner_group is not None:
            self.env_runner_group.stop()
