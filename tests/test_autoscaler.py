"""Autoscaler tests over the local subprocess provider (reference analog:
python/ray/tests/test_autoscaler_fake_multinode.py over
FakeMultiNodeProvider)."""

from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (Autoscaler, AutoscalerConfig,
                                LocalSubprocessProvider, NodeTypeConfig)


@pytest.fixture()
def head():
    rt = ray_tpu.init(num_cpus=0, num_tpus=0, head_port=0,
                      cluster_token=b"astok")
    yield rt
    ray_tpu.shutdown()


def _make(rt, node_types, idle_timeout_s=3600.0):
    provider = LocalSubprocessProvider(rt.head_server.address, b"astok")
    asc = Autoscaler(rt, provider, AutoscalerConfig(
        node_types=node_types, idle_timeout_s=idle_timeout_s,
        update_interval_s=0.3))
    return provider, asc


def _wait(pred, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


class TestAutoscaler:
    def test_demand_driven_scale_up(self, head):
        provider, asc = _make(head, {
            "cpu2": NodeTypeConfig(resources={"CPU": 2}, max_workers=3)})
        try:
            # No nodes yet: this task is infeasible until a node appears.
            @ray_tpu.remote(num_cpus=1)
            def f(x):
                return x + 1

            ref = f.remote(41)
            assert ray_tpu.get(ref, timeout=90) == 42
            assert len(provider.non_terminated_nodes()) >= 1
        finally:
            asc.stop()
            provider.shutdown()

    def test_scale_up_to_fit_parallel_demand(self, head):
        provider, asc = _make(head, {
            "cpu2": NodeTypeConfig(resources={"CPU": 2}, max_workers=4)})
        try:
            @ray_tpu.remote(num_cpus=2)
            def hold(t):
                time.sleep(t)
                return 1

            refs = [hold.remote(3.0) for _ in range(3)]
            assert sum(ray_tpu.get(refs, timeout=120)) == 3
            # 3 concurrent 2-CPU tasks needed 3 nodes.
            assert _wait(lambda: len(provider.non_terminated_nodes()) >= 3,
                         timeout=5)
        finally:
            asc.stop()
            provider.shutdown()

    def test_max_workers_cap(self, head):
        provider, asc = _make(head, {
            "cpu1": NodeTypeConfig(resources={"CPU": 1}, max_workers=2)})
        try:
            @ray_tpu.remote(num_cpus=1)
            def hold(t):
                time.sleep(t)
                return 1

            refs = [hold.remote(2.0) for _ in range(5)]
            assert sum(ray_tpu.get(refs, timeout=120)) == 5
            assert len(provider.non_terminated_nodes()) <= 2
        finally:
            asc.stop()
            provider.shutdown()

    def test_idle_downscale_respects_min(self, head):
        provider, asc = _make(head, {
            "cpu2": NodeTypeConfig(resources={"CPU": 2}, min_workers=1,
                                   max_workers=3)},
            idle_timeout_s=1.0)
        try:
            @ray_tpu.remote(num_cpus=2)
            def hold(t):
                time.sleep(t)
                return 1

            refs = [hold.remote(2.0) for _ in range(3)]
            assert sum(ray_tpu.get(refs, timeout=120)) == 3
            # After the work drains, idle nodes terminate down to min=1.
            assert _wait(lambda: len(provider.non_terminated_nodes()) == 1,
                         timeout=60)
        finally:
            asc.stop()
            provider.shutdown()

    def test_slice_gang_scales_whole_group_atomically(self, head):
        """A pending 2-host slice reservation (STRICT_SPREAD PG) launches
        exactly its node group — whole gang, nothing partial — and the PG
        commits once both join (reference: v2/scheduler.py:822 gang
        resource requests for multi-host TPU slices)."""
        provider, asc = _make(head, {
            "slice-host": NodeTypeConfig(
                resources={"CPU": 2, "slice_host": 1}, max_workers=4)})
        try:
            pg = ray_tpu.placement_group(
                [{"CPU": 2, "slice_host": 1},
                 {"CPU": 2, "slice_host": 1}],
                strategy="STRICT_SPREAD")
            assert pg.ready(timeout=120)
            # Exactly the gang size was launched: no partial fills, no
            # per-tick relaunch storm while the two nodes were joining.
            assert len(provider.non_terminated_nodes()) == 2
            # The reserved (but idle) slice is protected from idle
            # downscale until the reservation is dropped.
            asc.config.idle_timeout_s = 0.5
            time.sleep(2.0)
            assert len(provider.non_terminated_nodes()) == 2
            ray_tpu.remove_placement_group(pg)
            assert _wait(
                lambda: len(provider.non_terminated_nodes()) == 0,
                timeout=60)
        finally:
            asc.stop()
            provider.shutdown()

    def test_idle_downscale_drains_before_terminate(self, head):
        """Idle downscale must route through the PR 7 drain protocol:
        the victim appears DRAINING (fenced, reason=idle-downscale)
        while still provider-alive, and the provider terminate fires
        only after the fence settles — never the bare terminate that
        vaporized RAM-checkpoint replicas."""
        provider, asc = _make(head, {
            "cpu2": NodeTypeConfig(resources={"CPU": 2}, max_workers=2)},
            idle_timeout_s=1.0)
        asc.config.idle_drain_deadline_s = 2.5
        try:
            @ray_tpu.remote(num_cpus=2)
            def hold(t):
                time.sleep(t)
                return 1

            assert ray_tpu.get(hold.remote(1.0), timeout=90) == 1
            saw_draining_while_alive = False
            drain_reason = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                alive_pids = provider.non_terminated_nodes()
                if not alive_pids:
                    break
                for n in head.ctl_nodes():
                    if n["is_head"] or not n["alive"]:
                        continue
                    if n["draining"]:
                        saw_draining_while_alive = True
                        drain_reason = n["drain_reason"]
                time.sleep(0.05)
            assert saw_draining_while_alive, \
                "node terminated without ever draining"
            assert drain_reason == "idle-downscale"
            assert _wait(
                lambda: len(provider.non_terminated_nodes()) == 0,
                timeout=30)
        finally:
            asc.stop()
            provider.shutdown()

    def test_partial_gang_loss_relaunches_missing_bundles_only(self, head):
        """A pending slice gang that loses a node mid-boot re-launches
        ONLY the missing bundles — never a second full gang (the
        join-expectation accounting must survive a mid-boot death)."""
        provider, asc = _make(head, {
            "slice-host": NodeTypeConfig(
                resources={"CPU": 2, "slice_host": 1}, max_workers=4)})
        # Widen the mid-boot window so the kill lands before the join.
        provider.boot_delay_s = 1.5
        try:
            pg = ray_tpu.placement_group(
                [{"CPU": 2, "slice_host": 1},
                 {"CPU": 2, "slice_host": 1}],
                strategy="STRICT_SPREAD")
            # Wait for the 2-node gang launch, then lose one mid-boot.
            assert _wait(lambda: provider._next >= 2, timeout=30)
            victim = provider.non_terminated_nodes()[0]
            provider.lose_instance(victim)
            assert pg.ready(timeout=120)
            # Exactly ONE relaunch: 2 (gang) + 1 (replacement), and no
            # per-tick relaunch storm afterwards.
            assert provider._next == 3, provider._next
            time.sleep(2.0)
            assert provider._next == 3, provider._next
            assert len(provider.non_terminated_nodes()) == 2
            ray_tpu.remove_placement_group(pg)
        finally:
            asc.stop()
            provider.shutdown()
