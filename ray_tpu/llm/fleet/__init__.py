"""ray_tpu.llm.fleet — multi-replica decode serving.

The serving fleet: N continuous-batching decode replicas behind the
disagg admission router, with prefix-cache-affinity routing (longest
shared prompt prefix wins, load-imbalance override), a shared prefill
tier whose KV handoffs ride the shm object store same-host and the p2p
pull path cross-host, and SLO-driven replica autoscaling off the
metricsview backplane (queue depth / shed rate / ITL p99).  Reference
analog: the reference's multi-replica LLM serving deployments — vLLM
engines behind a prefix-aware router with replica autoscaling.
"""

from .autoscale import (FleetScaleDecision, ServeAutoscalePolicy,
                        ServeScaleConfig)
from .prefix import (DEFAULT_BLOCK, PrefixCache, full_hash, prefix_chain,
                     score_summary)
from .remote import RemoteReplica, ReplicaHost
from .replica import DecodeReplica
from .router import FleetRouter, RouteDecision, RoutingConfig
from .server import FLEET_KV_PREFIX, FleetConfig, FleetServer

__all__ = [
    "DEFAULT_BLOCK", "PrefixCache", "prefix_chain", "full_hash",
    "score_summary",
    "DecodeReplica", "RemoteReplica", "ReplicaHost",
    "FleetRouter", "RouteDecision", "RoutingConfig",
    "ServeAutoscalePolicy", "ServeScaleConfig", "FleetScaleDecision",
    "FleetConfig", "FleetServer", "FLEET_KV_PREFIX",
]
