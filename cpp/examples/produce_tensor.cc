// Native data producer: writes two tensors into a shm segment that
// Python maps zero-copy (ray_tpu.util.cpp_io.import_tensors) and feeds
// to jax.device_put — the native-loader half of the IO path.
//
//   g++ -std=c++17 -O2 -Icpp/include cpp/examples/produce_tensor.cc \
//       -o produce_tensor -lrt
//   ./produce_tensor /my_batch 8
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "ray_tpu/tensor_writer.hpp"

int main(int argc, char **argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <segment> <batch>\n", argv[0]);
    return 2;
  }
  const std::string segment = argv[1];
  const uint64_t batch = std::strtoull(argv[2], nullptr, 10);

  ray_tpu::TensorWriter w(segment);
  size_t x = w.add(ray_tpu::F32, {batch, 16});
  size_t y = w.add(ray_tpu::I32, {batch});

  auto *xs = reinterpret_cast<float *>(w.data(x));
  for (uint64_t i = 0; i < batch * 16; ++i) {
    xs[i] = static_cast<float>(i) * 0.5f;
  }
  auto *ys = reinterpret_cast<int32_t *>(w.data(y));
  for (uint64_t i = 0; i < batch; ++i) {
    ys[i] = static_cast<int32_t>(i * i);
  }
  w.finish();
  std::printf("wrote %s\n", segment.c_str());
  return 0;
}
