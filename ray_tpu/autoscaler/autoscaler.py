"""The reconciler: demand -> desired node set -> provider actions.

Reference: v2 Autoscaler (autoscaler.py:51) update loop — read demand,
run the ResourceDemandScheduler bin-packing (v2/scheduler.py:822), diff
against the instance manager's view, launch/terminate.  Simplifications
kept honest: first-fit-decreasing bin-packing over configured node types,
idle-timeout downscaling (a node with no running work past the timeout),
min/max clamps per type.

Preemption-aware on top (the closed elasticity loop): an attached
``GoodputAutoscalePolicy`` pre-buys a replacement the moment a drain
notice lands on a node that work occupies — before the deadline, not
after the death — and buys capacity when the live goodput ratio sags
below its floor; a draining node holding committed slice-gang bundles
triggers a whole-slice replacement gang (all-or-nothing, agreeing with
the scheduler's drain fence); and idle downscale routes through the
drain protocol instead of vaporizing RAM-checkpoint replicas with a
bare terminate.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..util import telemetry
from .providers import NodeProvider

#: KV key the reconcile loop publishes its live status under (read by
#: ``ray-tpu status`` / cluster_status next to the goodput line; same
#: last-writer ``diagnostics/`` convention as the mesh/watchdog records).
AUTOSCALER_KV_KEY = "diagnostics/autoscaler/status"


@dataclass
class NodeTypeConfig:
    """reference: available_node_types entries in the autoscaler yaml."""
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0
    #: Idle downscale drains the victim first (PR 7 protocol: fence ->
    #: evacuate RAM replicas / pinned blobs) and terminates only after
    #: this deadline settles — never a bare provider.terminate_node.
    idle_drain_deadline_s: float = 5.0
    #: Goodput-driven scaling + pre-buy-on-notice policy (None: the
    #: preemption-naive reconciler, demand-reactive only).
    policy: Optional["GoodputAutoscalePolicy"] = None
    #: Pending pre-buys older than this stop counting against
    #: max_pending_prebuys (join-confirmation backstop for providers
    #: without node_os_pid; generously above any sane boot time).
    prebuy_pending_ttl_s: float = 180.0


class Autoscaler:
    """Reconciles cluster size against scheduler demand."""

    def __init__(self, runtime, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.runtime = runtime
        self.provider = provider
        self.config = config
        # provider_id -> (node_type, launch_ts)
        self._launched: Dict[str, tuple] = {}
        # provider_id -> expected alive-worker count once this launch
        # joins (pid-less providers only; see _gang_launches fallback).
        self._expected_alive: Dict[str, int] = {}
        # node_id (runtime) -> first-seen-idle timestamp
        self._idle_since: Dict = {}
        # Pre-buys in flight: provider_id -> {"victim", "reason", "ts"}.
        self._prebuys: Dict[str, Dict] = {}
        self.prebuy_total = 0
        # Idle-downscale drains awaiting their fence: node_id hex ->
        # {"pid", "ntype", "deadline"} (terminate fires after deadline).
        self._idle_drains: Dict[str, Dict] = {}
        # (pg_id, node_id) pairs whose draining slice-gang bundle already
        # bought its whole-slice replacement (fire once per drain).
        self._slice_prebought: Set[Tuple] = set()
        self._status_pub_mono = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- loop ---------------------------------------------------------------

    def _loop(self) -> None:
        # Satisfy min_workers immediately.
        for name, ntc in self.config.node_types.items():
            for _ in range(ntc.min_workers):
                self._launch(name, ntc)
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self._reconcile()
            except Exception:
                import traceback
                traceback.print_exc()

    def _count_by_type(self) -> Dict[str, int]:
        live = set(self.provider.non_terminated_nodes())
        counts: Dict[str, int] = {}
        for pid, (ntype, _ts) in list(self._launched.items()):
            if pid in live:
                counts[ntype] = counts.get(ntype, 0) + 1
            else:
                self._launched.pop(pid, None)
                self._expected_alive.pop(pid, None)
        return counts

    def _alive_workers(self) -> int:
        return sum(1 for n in self.runtime.controller.alive_nodes()
                   if not n.is_head)

    def _busy_nodes(self) -> set:
        """Runtime node ids holding running tasks, actors, or committed
        placement-group bundles (reserved slices are busy, not idle)."""
        rt = self.runtime
        busy = set()
        with rt._running_lock:
            for t in rt._running.values():
                busy.add(t.node_id)
        with rt._actors_lock:
            for ast in rt._actors.values():
                if ast.node_id is not None:
                    busy.add(ast.node_id)
        from .._private.controller import PG_REMOVED
        for pg in rt.controller.placement_groups.values():
            if pg.state == PG_REMOVED:
                continue
            for b in pg.bundles:
                if b.node_id is not None:
                    busy.add(b.node_id)
        return busy

    def _launch(self, name: str, ntc: NodeTypeConfig) -> str:
        pid = self.provider.create_node(name, ntc.resources)
        # Join expectation: the worker count this launch should bring the
        # cluster to.  Base = max(current count, any still-unmet RECENT
        # expectation) so concurrent launches stack (+1 each) and foreign
        # or pre-existing nodes — counted in the base — never satisfy it.
        # Stale expectations (launch never joined within 120s: spawn
        # failure) are dropped here, not ratcheted into the base — one
        # dead launch must not inflate every future expectation.
        now = time.monotonic()
        for p in list(self._expected_alive):
            ts = self._launched.get(p)
            if ts is None or now - ts[1] > 120.0:
                self._expected_alive.pop(p, None)
        base = max([self._alive_workers()]
                   + list(self._expected_alive.values()))
        self._expected_alive[pid] = base + 1
        self._launched[pid] = (name, now)
        return pid

    # -- goodput policy / pre-buy -------------------------------------------

    def _joined_os_pids(self) -> set:
        joined = set()
        for n in self.runtime.controller.alive_nodes():
            try:
                joined.add(int(n.labels.get("os_pid", 0)))
            except (TypeError, ValueError):
                pass
        joined.discard(0)
        return joined

    def _prune_prebuys(self) -> int:
        """Drop pre-buys that joined (no longer pending) or died before
        joining (spawn failure); returns the still-pending count and
        refreshes the gauge the status line reads."""
        live = set(self.provider.non_terminated_nodes())
        get_pid = getattr(self.provider, "node_os_pid", None)
        joined = self._joined_os_pids()
        now = time.monotonic()
        for pid, rec in list(self._prebuys.items()):
            if pid not in live:
                self._prebuys.pop(pid, None)
                continue
            # TTL backstop: a provider without node_os_pid (real cloud
            # providers) can never confirm the join, and a wedged entry
            # would saturate the pending bound and disable pre-buying
            # forever.  Past the TTL the node either joined long ago or
            # never will — both stop counting against the bound.
            if now - rec["ts"] >= self.config.prebuy_pending_ttl_s:
                self._prebuys.pop(pid, None)
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None and os_pid in joined:
                self._prebuys.pop(pid, None)
        telemetry.set_gauge("ray_tpu_autoscaler_pending_prebuys",
                            float(len(self._prebuys)))
        return len(self._prebuys)

    def _policy_scale(self, counts: Dict[str, int]) -> None:
        """One policy tick: feed the live goodput summary + the
        preemption-notice stream (draining nodes that work occupies)
        into the GoodputAutoscalePolicy and execute its buy decisions.
        Mutates ``counts`` with the launches so the demand math below
        sees them."""
        policy = self.config.policy
        if policy is None:
            return
        policy.observe_goodput(telemetry.goodput_summary())
        busy = self._busy_nodes()
        get_pid = getattr(self.provider, "node_os_pid", None)
        type_by_os: Dict[int, str] = {}
        if get_pid is not None:
            for pid, (ntype, _ts) in list(self._launched.items()):
                os_pid = get_pid(pid)
                if os_pid:
                    type_by_os[os_pid] = ntype
        # Nodes holding committed slice-gang bundles are the
        # whole-slice launcher's problem (_slice_gang_prebuy buys the
        # full gang all-or-nothing) — a per-victim pre-buy here would
        # buy the same replacement twice, or at max_workers eat the
        # headroom the gang check needs.
        from .._private.controller import PG_CREATED
        gang_owned = set()
        for pg in self.runtime.controller.placement_groups.values():
            if pg.state == PG_CREATED and pg.strategy == "STRICT_SPREAD":
                for b in pg.bundles:
                    if b.node_id is not None:
                        gang_owned.add(b.node_id)
        notices: List = []
        draining_by_type: Dict[str, int] = {}
        # Victims whose type can't be resolved (pid-less cloud
        # providers) still free a slot when they die — counted as a
        # type-blind discount so pre-buy keeps working at max_workers
        # on exactly the providers it was built for.
        draining_untyped = 0
        for n in self.runtime.controller.draining_nodes():
            if n.is_head or n.node_id not in busy \
                    or n.node_id in gang_owned:
                continue
            try:
                os_pid = int(n.labels.get("os_pid", 0))
            except (TypeError, ValueError):
                os_pid = 0
            ntype = type_by_os.get(os_pid)
            notices.append((n.node_id.hex(), ntype))
            if ntype is not None:
                draining_by_type[ntype] = \
                    draining_by_type.get(ntype, 0) + 1
            else:
                draining_untyped += 1
        pending = self._prune_prebuys()
        for d in policy.decide(notices, pending):
            ntype = d.node_type or next(iter(self.config.node_types))
            ntc = self.config.node_types.get(ntype)
            if ntc is None:
                # Unknown type (config rename/typo): un-commit so a
                # later notice can retry, same as the headroom drop.
                if d.victim:
                    policy.forget_victim(d.victim)
                if d.reason == "goodput":
                    policy.forget_goodput_buy()
                continue
            # Headroom judged minus the doomed (draining) nodes: a
            # pre-buy replaces one of them, it does not grow the
            # steady-state fleet past max_workers.
            effective = counts.get(ntype, 0) - \
                draining_by_type.get(ntype, 0) - draining_untyped
            if effective >= ntc.max_workers:
                # Un-commit the dropped decision so a later tick with
                # headroom can retry (re-notice / next sag window).
                if d.victim:
                    policy.forget_victim(d.victim)
                if d.reason == "goodput":
                    policy.forget_goodput_buy()
                continue
            pid = self._launch(ntype, ntc)
            self._prebuys[pid] = {"victim": d.victim,
                                  "reason": d.reason,
                                  "ts": time.monotonic()}
            # Counters book EXECUTED buys only — decide() may emit
            # decisions the headroom check above drops.
            if d.reason == "prebuy":
                self.prebuy_total += d.count
                telemetry.inc("ray_tpu_autoscaler_prebuy_total",
                              d.count)
            else:
                telemetry.inc(
                    "ray_tpu_autoscaler_goodput_scale_events_total",
                    d.count, tags={"direction": "up"})
            counts[ntype] = counts.get(ntype, 0) + 1
        telemetry.set_gauge("ray_tpu_autoscaler_pending_prebuys",
                            float(len(self._prebuys)))

    def _slice_gang_prebuy(self, counts: Dict[str, int]) -> Dict[str, int]:
        """A draining node holding committed slice-gang bundles
        (STRICT_SPREAD — the SlicePlacementGroup shape) dooms those
        bundles at its deadline: pre-buy the replacement node group as
        ONE all-or-nothing gang so the scheduler's post-death re-plan
        (reschedule_lost_bundles, which only re-plans the lost bundles)
        finds capacity waiting.  The drain fence and this launcher
        agree: draining nodes are not schedulable capacity, so the
        feasibility check below never counts them.  Fires once per
        (pg, node) drain; other slices' committed bundles are never
        touched."""
        policy = self.config.policy
        if policy is None or not policy.config.prebuy:
            return {}
        from .._private.controller import PG_CREATED
        draining = {n.node_id for n in
                    self.runtime.controller.draining_nodes()}
        if not draining:
            self._slice_prebought.clear()
            return {}
        to_launch: Dict[str, int] = {}
        for pg in list(self.runtime.controller.placement_groups.values()):
            if pg.state != PG_CREATED or pg.strategy != "STRICT_SPREAD":
                continue
            doomed = [b for b in pg.bundles if b.node_id in draining]
            if not doomed or all((pg.pg_id, b.node_id) in
                                 self._slice_prebought for b in doomed):
                continue
            shapes = [b.resources.to_dict() for b in doomed]
            # All-or-nothing: one node type must fit every doomed
            # bundle with headroom for the full replacement gang
            # (victims are doomed, so they free their slots).
            gang_type = None
            for name, ntc in self.config.node_types.items():
                if all(all(ntc.resources.get(k, 0.0) >= v
                           for k, v in s.items()) for s in shapes):
                    # Victims free their slots when they die and the
                    # gang replaces them 1:1, so steady-state count
                    # stays at `have`.
                    have = counts.get(name, 0) + to_launch.get(name, 0)
                    if have <= ntc.max_workers:
                        gang_type = name
                        break
            if gang_type is None:
                continue  # nothing partial: the whole gang or no buy
            for b in doomed:
                self._slice_prebought.add((pg.pg_id, b.node_id))
            to_launch[gang_type] = \
                to_launch.get(gang_type, 0) + len(shapes)
            self.prebuy_total += len(shapes)
            telemetry.inc("ray_tpu_autoscaler_prebuy_total", len(shapes))
        return to_launch

    def _publish_status(self, counts: Dict[str, int]) -> None:
        """Drop the live reconcile view into the head KV (rate-limited,
        best-effort) for `ray-tpu status` / cluster_status: pending
        pre-buys belong next to the goodput they protect."""
        now = time.monotonic()
        if now - self._status_pub_mono < 1.0:
            return
        self._status_pub_mono = now
        policy = self.config.policy
        doc = {
            "pending_prebuys": len(self._prebuys),
            "prebuy_total": self.prebuy_total,
            "idle_draining": len(self._idle_drains),
            "nodes_by_type": dict(counts),
            "policy": policy.status() if policy is not None else None,
            "time": time.time(),
        }
        try:
            self.runtime.ctl_kv_put(AUTOSCALER_KV_KEY,
                                    json.dumps(doc).encode())
        except Exception as e:  # noqa: BLE001 — status is best-effort
            telemetry.note_swallowed("autoscaler.publish_status", e)

    def _gang_launches(self, counts: Dict[str, int]) -> Dict[str, int]:
        """Atomic multi-host gangs (pending slice/STRICT_SPREAD placement
        groups): launch the WHOLE node group or nothing (reference:
        v2/scheduler.py:822 gang resource requests).  Returns per-type
        launch counts; partial gangs are never launched."""
        gangs = self.runtime.scheduler.pending_gang_demand()
        if not gangs:
            return {}
        # Launches in flight (created by US but not yet registered with
        # the runtime, matched by OS pid): wait for them to land before
        # judging gang feasibility, or every tick would launch another
        # full gang.  Nodes that never join stop blocking after a
        # timeout (spawn failure), and foreign/manual nodes are ignored.
        joined_os_pids = self._joined_os_pids()
        get_pid = getattr(self.provider, "node_os_pid", None)
        live = set(self.provider.non_terminated_nodes())
        now = time.monotonic()
        n_alive = self._alive_workers()
        for pid, (_ntype, ts) in self._launched.items():
            if pid not in live:
                continue
            if self._expected_alive.get(pid, 0) <= n_alive:
                # Met (or pid-matched provider): stop tracking so later
                # downscales don't inflate future launch baselines.
                self._expected_alive.pop(pid, None)
            if now - ts > 120.0:
                # Never joined: spawn failure — stop blocking AND stop
                # counting toward future launch baselines.
                self._expected_alive.pop(pid, None)
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None:
                if os_pid not in joined_os_pids:
                    return {}  # still joining; don't double-buy
            elif pid in self._expected_alive:
                # Pid-less provider (cloud/TPU-pod): the worker count
                # hasn't reached this launch's expectation yet, so the
                # node is still booting (a multi-host slice takes
                # minutes) — launching another full gang each tick would
                # over-provision entire TPU slices.
                return {}
        per_node = self.runtime.scheduler.per_node_available()
        to_launch: Dict[str, int] = {}
        for strategy, shapes, placed_nodes in gangs:
            if strategy == "STRICT_PACK":
                # One node must hold every bundle: treat as a single
                # summed shape.
                total: Dict[str, float] = {}
                for s in shapes:
                    for k, v in s.items():
                        total[k] = total.get(k, 0.0) + v
                shapes = [total]
                distinct = False
            else:
                # STRICT_SPREAD (the TPU-slice gang) and SPREAD want
                # bundle-per-node; PACK tolerates co-location but a
                # node-per-bundle launch always satisfies it.
                distinct = strategy in ("STRICT_SPREAD", "SPREAD")
            # Nodes already holding this PG's bundles can't take more of
            # its spread bundles (mirrors the scheduler's used_nodes
            # exclusion) — judging them free would deadlock a partially
            # placed gang after a node loss.
            occupied = set(placed_nodes)
            free_nodes = [dict(v) for nid, v in per_node.items()
                          if not distinct or nid not in occupied]
            needed: List[Dict[str, float]] = []
            for shape in shapes:
                placed = False
                for fn in free_nodes:
                    if all(fn.get(k, 0.0) >= v for k, v in shape.items()):
                        if distinct:
                            free_nodes.remove(fn)
                        else:
                            for k, v in shape.items():
                                fn[k] = fn.get(k, 0.0) - v
                        placed = True
                        break
                if not placed:
                    needed.append(shape)
            if not needed:
                continue  # scheduler will commit on its next retry
            # All-or-nothing: find one type fitting every missing bundle
            # with enough max_workers headroom for the full gang.
            gang_type = None
            for name, ntc in self.config.node_types.items():
                if all(all(ntc.resources.get(k, 0.0) >= v
                           for k, v in shape.items()) for shape in needed):
                    have = counts.get(name, 0) + to_launch.get(name, 0)
                    if have + len(needed) <= ntc.max_workers:
                        gang_type = name
                        break
            if gang_type is None:
                continue  # unplaceable gang stays pending (status surfaces)
            to_launch[gang_type] = to_launch.get(gang_type, 0) + len(needed)
        return to_launch

    def _reconcile(self) -> None:
        counts = self._count_by_type()
        # Preemption-aware layer first: pre-buy replacements for noticed
        # victims (and goodput-sag capacity) before the demand math —
        # the whole point is to spend the drain deadline booting.
        self._policy_scale(counts)
        for name, n in self._slice_gang_prebuy(counts).items():
            counts[name] = counts.get(name, 0) + n
            for _ in range(n):
                pid = self._launch(name, self.config.node_types[name])
                self._prebuys[pid] = {"victim": None,
                                      "reason": "slice_gang",
                                      "ts": time.monotonic()}
        # Gangs next: a pending slice reservation launches its whole
        # node group atomically, before flat demand claims headroom.
        gang_launch = self._gang_launches(counts)
        for name, n in gang_launch.items():
            counts[name] = counts.get(name, 0) + n
            for _ in range(n):
                self._launch(name, self.config.node_types[name])
        demand = self.runtime.scheduler.pending_demand(
            include_pg_bundles=False)

        # -- upscale: first-fit-decreasing bin-pack of unmet demand onto
        # node types (reference: v2/scheduler.py bin-packing). Capacity
        # already free in the cluster absorbs demand first (aggregate
        # pool approximation; per-node packing is the scheduler's job).
        pool = dict(self.runtime.ctl_available_resources())

        def fits_pool(shape: Dict[str, float]) -> bool:
            return all(pool.get(k, 0.0) >= v for k, v in shape.items())

        unmet: List[Dict[str, float]] = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            if fits_pool(shape):
                for k, v in shape.items():
                    pool[k] = pool.get(k, 0.0) - v
            else:
                unmet.append(shape)

        to_launch: Dict[str, int] = {}
        virtual: List[Dict[str, float]] = []
        for shape in unmet:
            placed = False
            for v in virtual:
                if all(v.get(k, 0.0) >= amt for k, amt in shape.items()):
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    placed = True
                    break
            if placed:
                continue
            for name, ntc in self.config.node_types.items():
                have = counts.get(name, 0) + to_launch.get(name, 0)
                if have >= ntc.max_workers:
                    continue
                if all(ntc.resources.get(k, 0.0) >= amt
                       for k, amt in shape.items()):
                    to_launch[name] = to_launch.get(name, 0) + 1
                    v = dict(ntc.resources)
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    virtual.append(v)
                    placed = True
                    break
            # Unplaceable on any type: stays pending (surfaced by status).
        for name, n in to_launch.items():
            for _ in range(n):
                self._launch(name, self.config.node_types[name])

        # -- downscale: drain-then-terminate nodes idle past the timeout,
        # respecting per-type minimums (reference: idle node termination
        # in v1/v2, routed through the PR 7 drain protocol).
        if not demand:
            self._downscale_idle(counts)
        self._publish_status(counts)

    def _downscale_idle(self, counts: Dict[str, int]) -> None:
        """Two-phase idle downscale.  Phase 1 marks an idle victim
        DRAINING (``ctl_drain_node`` with a short deadline) instead of
        terminating it outright: the fence makes it unschedulable while
        RAM-checkpoint replicas and pinned blobs evacuate through the
        drain protocol's listeners.  Phase 2 terminates only after the
        fence settles (deadline passed) — a bare provider.terminate_node
        on an idle node vaporized whatever it still hosted."""
        rt = self.runtime
        now = time.monotonic()
        busy_nodes = self._busy_nodes()

        # Phase 2: victims whose drain deadline settled terminate now.
        freed: Dict[str, int] = {}
        alive_hex = {n.node_id.hex(): n
                     for n in rt.controller.alive_nodes()}
        for hexid, rec in list(self._idle_drains.items()):
            if hexid not in alive_hex:
                # Died on its own mid-drain: provider bookkeeping only
                # (already absent from this tick's provider counts).
                self.provider.terminate_node(rec["pid"])
                self._launched.pop(rec["pid"], None)
                self._idle_drains.pop(hexid, None)
            elif now >= rec["deadline"]:
                self.provider.terminate_node(rec["pid"])
                self._launched.pop(rec["pid"], None)
                self._idle_drains.pop(hexid, None)
                # ``counts`` was snapshotted while this victim was
                # still provider-alive, and the pop above hides it from
                # the draining decrement below — without this, the tick
                # a drain settles could drain ANOTHER node past
                # min_workers.
                freed[rec["ntype"]] = freed.get(rec["ntype"], 0) + 1

        # Phase 1: idle detection on runtime node ids; the drain targets
        # the youngest idle provider node of a type over its minimum.
        # (The provider only knows pids; the runtime only knows node
        # ids — matched by the OS pid each node reported at
        # registration.)
        alive = [n for n in rt.controller.alive_nodes() if not n.is_head]
        drain_pids = {rec["pid"] for rec in self._idle_drains.values()}
        idle_os_pids = set()
        os_to_hex: Dict[int, str] = {}
        for n in alive:
            hexid = n.node_id.hex()
            if n.node_id in busy_nodes or hexid in self._idle_drains:
                if n.node_id in busy_nodes:
                    self._idle_since.pop(n.node_id, None)
                continue
            first = self._idle_since.setdefault(n.node_id, now)
            if now - first >= self.config.idle_timeout_s:
                try:
                    os_pid = int(n.labels.get("os_pid", 0))
                except (TypeError, ValueError):
                    continue
                if os_pid:
                    idle_os_pids.add(os_pid)
                    os_to_hex[os_pid] = hexid
        if not idle_os_pids:
            return
        get_pid = getattr(self.provider, "node_os_pid", None)
        remaining = dict(counts)
        # Nodes already draining toward termination — and ones Phase 2
        # terminated this very tick — count as gone for the per-type
        # minimum.
        for rec in self._idle_drains.values():
            remaining[rec["ntype"]] = remaining.get(rec["ntype"], 0) - 1
        for ntype, n in freed.items():
            remaining[ntype] = remaining.get(ntype, 0) - n
        for pid, (ntype, _ts) in list(self._launched.items()):
            if pid in drain_pids:
                continue
            if remaining.get(ntype, 0) <= \
                    self.config.node_types[ntype].min_workers:
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None and os_pid in idle_os_pids:
                hexid = os_to_hex[os_pid]
                if not rt.ctl_drain_node(
                        hexid, self.config.idle_drain_deadline_s,
                        "idle-downscale"):
                    continue  # node vanished between scan and drain
                self._idle_drains[hexid] = {
                    "pid": pid, "ntype": ntype,
                    "deadline": now + self.config.idle_drain_deadline_s}
                remaining[ntype] = remaining.get(ntype, 0) - 1
                if self.config.policy is not None:
                    telemetry.inc(
                        "ray_tpu_autoscaler_goodput_scale_events_total",
                        tags={"direction": "down"})

    # -- introspection ------------------------------------------------------

    def status(self) -> Dict:
        policy = self.config.policy
        return {
            "nodes_by_type": self._count_by_type(),
            "pending_demand": len(self.runtime.scheduler.pending_demand()),
            "pending_prebuys": len(self._prebuys),
            "prebuy_total": self.prebuy_total,
            "idle_draining": len(self._idle_drains),
            "policy": policy.status() if policy is not None else None,
        }
