"""Collective nodes for compiled DAGs: allreduce across actor outputs.

Reference: python/ray/dag/collective_node.py:23 (_CollectiveOperation
binding N actor-method outputs to an NCCL allreduce, producing N outputs)
and ray.experimental.collective.allreduce.

TPU-first stance: *device* tensors inside SPMD programs reduce via XLA
collectives (psum over the mesh) inside jit — that path never touches the
DAG layer.  DAG collectives cover the host side: CPU numpy pytrees owned
by separate actor processes (e.g. per-actor gradient shards in a
parameter-server-free setup) reduced without a driver round-trip.  The
compiled form wires peer-to-peer shm channels between every pair of
participants: each actor broadcasts its contribution and reduces locally —
one iteration, no central hop, deadlock-free with capacity-1 channels
because all writes precede all reads.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

REDUCE_OPS = ("sum", "mean", "max", "min")


def _tree_reduce(op: str, values: List[Any]) -> Any:
    """Elementwise reduction over a list of same-structure pytrees."""
    import jax
    if op == "sum":
        fn = lambda *xs: sum(np.asarray(x) for x in xs)  # noqa: E731
    elif op == "mean":
        fn = lambda *xs: sum(np.asarray(x) for x in xs) / len(xs)  # noqa: E731
    elif op == "max":
        fn = lambda *xs: np.maximum.reduce([np.asarray(x) for x in xs])  # noqa: E731
    else:
        fn = lambda *xs: np.minimum.reduce([np.asarray(x) for x in xs])  # noqa: E731
    return jax.tree.map(fn, *values)


class CollectiveGroup:
    """One allreduce over N same-structure contributions, one per actor."""

    def __init__(self, inputs: List[Any], op: str):
        from . import ClassMethodNode
        if op not in REDUCE_OPS:
            raise ValueError(f"unsupported collective op {op!r}; "
                             f"one of {REDUCE_OPS}")
        if len(inputs) < 2:
            raise ValueError("collective needs >= 2 participants")
        actor_ids = []
        for n in inputs:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "collective participants must be actor method nodes, "
                    f"got {type(n).__name__}")
            actor_ids.append(n._actor._actor_id)
        if len(set(actor_ids)) != len(actor_ids):
            raise ValueError(
                "collective participants must live on distinct actors "
                "(reference: collective_node.py same constraint)")
        self.inputs = list(inputs)
        self.op = op


from ray_tpu.dag import DAGNode  # noqa: E402  (set by __init__ before the
#                                  tail `from .collective import ...`)


class CollectiveOutputNode(DAGNode):
    """The reduced value as seen by participant ``rank``'s actor.

    Downstream steps on that actor consume it locally; it can also be a
    DAG output.  The compiled planner special-cases it into a peer-to-peer
    broadcast + local reduction step.
    """

    def __init__(self, group: CollectiveGroup, rank: int):
        self._group = group
        self._rank = rank
        self._actor = group.inputs[rank]._actor

    def _upstream(self):
        # Depends on every participant's input: the collective cannot fire
        # until all contributions exist (this also gives the compiler the
        # right topo order).
        return list(self._group.inputs)

    def _eval_impl(self, memo, args, kwargs):
        """Interpreted mode: reduce on the driver (reference: interpreted
        collective falls back to object-store gather)."""
        import ray_tpu
        gkey = ("collective", id(self._group))
        if gkey not in memo:
            refs = [n._eval(memo, args, kwargs)
                    for n in self._group.inputs]
            values = ray_tpu.get(list(refs))
            memo[gkey] = _tree_reduce(self._group.op, values)
        return memo[gkey]

    def __repr__(self):
        return (f"CollectiveOutputNode({self._group.op}, rank={self._rank}, "
                f"actor={self._actor._class_name})")


def allreduce_bind(inputs: List[Any], op: str = "sum"
                   ) -> List[CollectiveOutputNode]:
    """Bind an allreduce across N actor-method nodes; returns one output
    node per participant, bound to the same actor (reference:
    ray.experimental.collective.allreduce.bind)."""
    group = CollectiveGroup(inputs, op)
    outputs = [CollectiveOutputNode(group, i) for i in range(len(inputs))]
    group.outputs = outputs
    return outputs
