"""Control-plane telescope: scheduler decision tracing + explanations.

The scheduler is the one subsystem whose failures are invisible by
default: a task that never places just sits in a queue, and nothing in
the task table says WHY.  This package holds the always-on, bounded
instrumentation that answers the two operator questions the reference's
`ray status -v` / autoscaler debug strings answer (reference:
python/ray/autoscaler/_private/util.py demand summaries +
src/ray/raylet/scheduling/ cluster_lease_manager's internal state):

* "why is this task still pending?" — unresolved deps by ObjectID, or
  the closest-fit node and the exact resource gap, or the drain fence /
  missing PG bundle that rejected it;
* "why did it land on node X?" — the recorded placement decision:
  scheduling class, candidate count, per-reason rejection tallies, the
  policy that picked the node, and the attempt number.

Pieces:

* :class:`DecisionRing` — a bounded ring of scheduler decision records
  (hot path = one ``deque.append``; folding into per-task state happens
  lazily at read time, the same trick ``_private/events.py`` uses).
* Reason codes (``R_*``) — the closed vocabulary every rejection is
  tallied under; `ray-tpu task why`, ``state.explain_task()`` and the
  ``sched_decisions.json`` flight-recorder section all speak it.
* ``set_enabled()/enabled()`` — the instrumentation kill switch the
  ``bench.py --spec control_plane`` overhead phase toggles (and
  ``RAY_TPU_SCHED_TRACE=0`` for operators who want the last word).
"""

from .decisions import (DecisionRing, R_AFFINITY, R_BUNDLE, R_DRAINING,
                        R_INFEASIBLE, R_INSUFFICIENT, R_NO_NODES,
                        R_PENDING_DEPS, REASON_CODES, enabled, set_enabled)

__all__ = [
    "DecisionRing",
    "REASON_CODES",
    "R_AFFINITY",
    "R_BUNDLE",
    "R_DRAINING",
    "R_INFEASIBLE",
    "R_INSUFFICIENT",
    "R_NO_NODES",
    "R_PENDING_DEPS",
    "enabled",
    "set_enabled",
]
