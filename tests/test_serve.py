"""Serve tests (reference pattern: python/ray/serve/tests)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=2)
class Doubler:
    def __call__(self, payload):
        if isinstance(payload, dict):
            return {"doubled": payload.get("x", 0) * 2}
        return payload * 2

    def describe(self):
        import os
        return os.getpid()


class TestServeCore:
    def test_deploy_and_call(self, ray_start):
        handle = serve.run(Doubler.bind())
        out = ray_tpu.get(handle.remote(21), timeout=60)
        assert out == 42
        serve.shutdown()

    def test_two_replicas_distinct_processes(self, ray_start):
        handle = serve.run(Doubler.bind())
        pids = set()
        for _ in range(20):
            pids.add(ray_tpu.get(handle.describe.remote(), timeout=60))
        assert len(pids) == 2
        serve.shutdown()

    def test_function_deployment(self, ray_start):
        @serve.deployment
        def greeter(payload):
            return f"hello {payload}"
        handle = serve.run(greeter.bind())
        assert ray_tpu.get(handle.remote("tpu"), timeout=60) == "hello tpu"
        serve.shutdown()

    def test_redeploy_replaces(self, ray_start):
        h1 = serve.run(Doubler.bind())
        ray_tpu.get(h1.remote(1), timeout=60)
        h2 = serve.run(Doubler.options(num_replicas=1).bind())
        assert ray_tpu.get(h2.remote(2), timeout=60) == 4
        assert serve.status()["Doubler"]["num_replicas"] == 1
        serve.shutdown()

    def test_init_args(self, ray_start):
        @serve.deployment
        class Scaler:
            def __init__(self, k):
                self.k = k

            def __call__(self, payload):
                return payload * self.k
        handle = serve.run(Scaler.bind(10))
        assert ray_tpu.get(handle.remote(4), timeout=60) == 40
        serve.shutdown()

    def test_http_ingress(self, ray_start):
        import json
        import urllib.request
        handle = serve.run(Doubler.bind(), http_port=18123)
        req = urllib.request.Request(
            "http://127.0.0.1:18123/Doubler",
            data=json.dumps({"x": 5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            body = json.loads(resp.read())
        assert body["result"] == {"doubled": 10}
        serve.shutdown()


class TestBatching:
    def test_batch_accumulates(self, ray_start):
        @serve.deployment
        class BatchAdder:
            @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
            def __call__(self, items):
                # Whole batch processed at once.
                return [i + 100 for i in items]

        handle = serve.run(BatchAdder.bind())
        refs = [handle.remote(i) for i in range(8)]
        out = sorted(ray_tpu.get(refs, timeout=60))
        assert out == [100 + i for i in range(8)]
        serve.shutdown()


class TestMultiplex:
    def test_lru_cache_and_eviction(self, ray_start):
        @serve.deployment(num_replicas=1)
        class MultiModel:
            def __init__(self):
                self.loads = []

            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id):
                self.loads.append(model_id)
                return f"model:{model_id}"

            def __call__(self, x):
                model = self.get_model()
                return {"model": model, "loads": list(self.loads),
                        "resident": self.get_model.loaded_model_ids}

        handle = serve.run(MultiModel.bind())

        def ask(mid):
            return ray_tpu.get(
                handle.options(multiplexed_model_id=mid).remote(0),
                timeout=60)

        r1 = ask("m1")
        assert r1["model"] == "model:m1" and r1["loads"] == ["m1"]
        ask("m2")
        r3 = ask("m1")          # cached — no reload
        assert r3["loads"] == ["m1", "m2"]
        r4 = ask("m3")          # evicts m2 (LRU)
        assert r4["loads"] == ["m1", "m2", "m3"]
        assert sorted(r4["resident"]) == ["m1", "m3"]
        r5 = ask("m2")          # m2 was evicted: reloaded
        assert r5["loads"] == ["m1", "m2", "m3", "m2"]
        serve.shutdown()

    def test_router_model_affinity(self, ray_start):
        @serve.deployment(num_replicas=2)
        class PidModel:
            @serve.multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id):
                return model_id

            def __call__(self, x):
                import os
                self.get_model()
                return os.getpid()

        handle = serve.run(PidModel.bind())
        h = handle.options(multiplexed_model_id="alpha")
        pids = [ray_tpu.get(h.remote(i), timeout=60) for i in range(6)]
        # After the first request establishes affinity, every later
        # request for the same model lands on the same replica.
        assert len(set(pids[1:])) == 1
        serve.shutdown()

    def test_model_id_outside_request_is_none(self, ray_start):
        from ray_tpu.serve import get_multiplexed_model_id
        assert get_multiplexed_model_id() is None


class TestServeControlPlane:
    """Reconciliation + autoscaling (reference:
    serve/_private/deployment_state.py:2795 reconcile loops,
    serve/autoscaling_policy.py)."""

    def test_dead_replica_recreated(self, ray_start):
        from ray_tpu import serve

        @serve.deployment(num_replicas=2)
        class Svc:
            def __call__(self, x):
                return x * 2

            def pid(self):
                import os
                return os.getpid()

        h = serve.run(Svc.bind())
        assert ray_tpu.get(h.remote(21), timeout=30) == 42
        state = serve.api._deployments["Svc"]
        victim = state.replicas[0]
        ray_tpu.kill(victim)
        # Controller notices the death and backfills to target.
        deadline = time.time() + 30
        while time.time() < deadline:
            with state._lock:
                live = [r for r in state.replicas if r is not victim]
                if victim not in state.replicas and len(state.replicas) == 2:
                    break
            time.sleep(0.1)
        with state._lock:
            assert victim not in state.replicas
            assert len(state.replicas) == 2
        # Requests still served after self-heal.
        assert ray_tpu.get(h.remote(5), timeout=30) == 10
        serve.shutdown()

    def test_autoscale_up_and_down(self, ray_start):
        from ray_tpu import serve
        from ray_tpu.serve import AutoscalingConfig

        @serve.deployment(
            num_replicas=1, max_ongoing_requests=4,
            autoscaling_config=AutoscalingConfig(
                min_replicas=1, max_replicas=3,
                target_ongoing_requests=1.0,
                upscale_delay_s=0.3, downscale_delay_s=0.6))
        class Slow:
            def __call__(self, t):
                time.sleep(t)
                return "done"

        h = serve.run(Slow.bind())
        state = serve.api._deployments["Slow"]
        # Load ramp: many slow concurrent requests -> queue depth >> target.
        refs = [h.remote(3.0) for _ in range(9)]
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(state.replicas) >= 3:
                break
            time.sleep(0.1)
        assert len(state.replicas) >= 3, "did not scale up"
        ray_tpu.get(refs, timeout=120)
        # Idle: scales back down to min.
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(state.replicas) == 1:
                break
            time.sleep(0.1)
        assert len(state.replicas) == 1, "did not scale down"
        serve.shutdown()

    def test_long_poll_push_on_change(self, ray_start):
        from ray_tpu import serve

        @serve.deployment(num_replicas=1)
        class P:
            def __call__(self, x):
                return x

        serve.run(P.bind())
        broker = serve.api._controller.broker
        v0, _ = broker.get("P")
        state = serve.api._deployments["P"]
        # Kill the only replica; the reconciler publishes a new snapshot.
        ray_tpu.kill(state.replicas[0])
        v1, snap = broker.wait_for_change("P", v0, timeout=30)
        assert v1 > v0
        serve.shutdown()
