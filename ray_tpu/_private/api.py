"""Public API objects: remote functions, actors, object refs, placement groups.

Maps the reference's Python API layer (reference:
python/ray/remote_function.py:41 RemoteFunction/_remote:314,
python/ray/actor.py:1445 ActorClass/_remote:1024, ActorHandle:2128,
ActorMethod:825, python/ray/includes/object_ref.pxi:50 ObjectRef) onto the
ray_tpu Runtime.  Both driver and worker processes use the same classes; the
runtime facade (``current_runtime``) routes calls to the in-process Runtime on
the driver or over the worker pipe inside tasks.
"""

from __future__ import annotations

import contextvars
import functools
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from . import serialization
from .config import Config
from .exceptions import RayTpuError
from .ids import ActorID, ObjectID, PlacementGroupID, TaskID
from .protocol import TaskSpec
from .resources import ResourceSet, task_resources
from . import runtime as _rtmod
from . import sanitizer as _sanitizer
from .runtime import current_runtime, driver_runtime
from ..util import tracing as _tracing
from .scheduler import (NodeAffinitySchedulingStrategy,
                        PlacementGroupSchedulingStrategy)


def _require_runtime():
    rt = current_runtime()
    if rt is None:
        raise RayTpuError("ray_tpu.init() has not been called")
    return rt


def _control(method: str, *args, **kwargs):
    rt = _require_runtime()
    if hasattr(rt, "control"):  # WorkerRuntime
        return rt.control(method, *args, **kwargs)
    return getattr(rt, "ctl_" + method)(*args, **kwargs)


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded results (reference:
    ObjectRefStream, task_manager.h:86; python num_returns="streaming").

    Iterating yields ObjectRefs one per generator item; the stream closes
    at the worker's ("end",) marker, and a mid-stream task error raises at
    the failing item's position when its ref is materialized."""

    def __init__(self, task_id: TaskID):
        self._task_id = task_id
        self._next = 0
        self._terminated = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        if self._terminated:
            raise StopIteration
        rt = current_runtime()
        oid = ObjectID.of(self._task_id, self._next)
        st = rt._state(oid) if hasattr(rt, "_state") else None
        if st is None:
            # worker-side facade: block through a get to learn the state
            raise RuntimeError(
                "ObjectRefGenerator iteration is driver-side only")
        st.wait()
        if isinstance(st.desc, tuple) and st.desc and st.desc[0] == "end":
            self._terminated = True
            raise StopIteration
        if isinstance(st.desc, tuple) and st.desc and st.desc[0] == "err":
            # The error is the stream's final item: consuming it raises,
            # and iteration ends (no index after the failure is ever
            # published).
            self._terminated = True
        self._next += 1
        return ObjectRef(oid)

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()})"


class ObjectRef:
    """Handle to a (possibly pending) immutable object
    (reference: python/ray/includes/object_ref.pxi:50).

    Driver-process refs are counted by the runtime's reference counter
    (reference: reference_counter.h:44 local refs): the last ref dropping
    frees the object.  Pickling a ref into user data marks the object
    escaped (a borrow the driver can't track), disabling auto-collection.
    """

    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: ObjectID):
        self._id = object_id
        wr = _rtmod._worker_runtime
        rt = _rtmod._global_runtime
        self._owned = rt is not None and wr is None
        if self._owned:
            rt.add_local_ref(object_id)
        elif wr is not None:
            # Worker-local direct-call results are ref-counted in the
            # worker's local table, and refs unpickled out of task args
            # register as borrows (no-op for client runtimes).
            note = getattr(wr, "note_new_ref", None)
            if note is not None:
                note(self)

    def __del__(self):
        # May run at arbitrary GC points: only a lock-free enqueue here
        # (the runtime's ref-gc thread applies the decrement).
        if getattr(self, "_owned", False):
            rt = _rtmod._global_runtime
            if rt is not None:
                try:
                    rt.enqueue_ref_drop(self._id)
                except Exception:
                    pass
        else:
            wr = _rtmod._worker_runtime
            drop = getattr(wr, "drop_local", None) if wr is not None else None
            if drop is not None:
                try:
                    drop(self._id.binary())
                except Exception:
                    pass

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def __reduce__(self):
        collector = _nested_collector.get()
        if getattr(self, "_owned", False):
            if collector is not None:
                # Pickling into task args: a tracked borrow (retained
                # until the task completes), not an escaped-forever pin.
                collector.append(self._id)
            else:
                rt = _rtmod._global_runtime
                if rt is not None:
                    rt.mark_escaped(self._id)
        else:
            wr = _rtmod._worker_runtime
            promote = getattr(wr, "promote_local", None) \
                if wr is not None else None
            if promote is not None:
                # A worker-local direct result leaving this process must
                # register with the head regardless of borrow tracking.
                try:
                    promote(self._id)
                except Exception:
                    pass
            if collector is not None:
                collector.append(self._id)
        return (ObjectRef, (self._id,))

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def future(self):
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def fill():
            try:
                fut.set_result(get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        _sanitizer.spawn(fill, name="ref-fill")
        return fut

    def __await__(self):
        """Support `await ref` inside async actors."""
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()


_env_cache: Dict[tuple, Any] = {}


def _env_cache_key(runtime_env) -> Optional[tuple]:
    try:
        pip = runtime_env.get("pip") or runtime_env.get("uv") or ()
        return (
            runtime_env.get("working_dir"),
            tuple(runtime_env.get("py_modules") or ()),
            tuple(sorted((runtime_env.get("env_vars") or {}).items())),
            tuple([pip] if isinstance(pip, str) else pip),
        )
    except Exception:
        return None


def _prepare_env(runtime_env):
    """Resolve working_dir/py_modules local paths into content-addressed
    package blobs (reference: runtime_env packaging.py).

    Cached per env spec: a directory is snapshotted ONCE per distinct
    spec (Ray's working_dir-upload-at-first-use semantics), so per-call
    re-zipping and per-task blob duplication don't happen — specs share
    one prepared dict (and its blob) by reference.
    """
    if not runtime_env:
        return runtime_env
    key = _env_cache_key(runtime_env)
    if key is not None and key in _env_cache:
        return _env_cache[key]
    from .runtime_env import prepare_runtime_env
    out = prepare_runtime_env(runtime_env)
    if key is not None and len(_env_cache) < 256:
        _env_cache[key] = out
    return out


# Active nested-ref collector: while packing task args, ObjectRefs pickled
# inside argument values land here (borrow tracking) instead of being
# marked escaped-forever (reference: reference_counter.h:44 borrows).
_nested_collector: "contextvars.ContextVar[Optional[list]]" = \
    contextvars.ContextVar("nested_ref_collector", default=None)


def _pack_arg(value: Any, collect_nested: Optional[list] = None):
    """Convert one call argument into a TaskSpec descriptor."""
    if isinstance(value, ObjectRef):
        return ("ref", value.id())
    if collect_nested is None:
        payload = serialization.pack_payload(value)
    else:
        token = _nested_collector.set(collect_nested)
        try:
            payload = serialization.pack_payload(value)
        finally:
            _nested_collector.reset(token)
    if len(payload) > Config.get("max_inline_object_size"):
        # Large argument: promote to an object so it travels via shm once.
        return ("ref", _put_value(value))
    return ("val", payload)


def _put_value(value: Any) -> ObjectID:
    rt = _require_runtime()
    return rt.put(value)


_nil_actor_cache: Dict[bytes, Any] = {}


def _next_task_id() -> TaskID:
    rt = _require_runtime()
    if hasattr(rt, "current_task_id") and rt.current_task_id is not None:
        return TaskID.of(rt.current_task_id.actor_id())
    if hasattr(rt, "current_actor_id") and rt.current_actor_id is not None:
        return TaskID.of(rt.current_actor_id)
    job = rt.job_id.binary()
    nil_actor = _nil_actor_cache.get(job)
    if nil_actor is None:
        from .ids import ActorID as _A
        nil_actor = _nil_actor_cache[job] = _A(job + b"\x00" * 8)
    return TaskID.of(nil_actor)


def _normalize_strategy(options: Dict[str, Any]):
    strategy = options.get("scheduling_strategy")
    pg, bundle = None, -1
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pgh = strategy.placement_group
        pg = pgh.id if isinstance(pgh, PlacementGroup) else pgh
        bundle = strategy.placement_group_bundle_index
        strategy = None
    if options.get("placement_group") is not None:
        pgh = options["placement_group"]
        pg = pgh.id if isinstance(pgh, PlacementGroup) else pgh
        bundle = options.get("placement_group_bundle_index", -1)
    return strategy, pg, bundle


def _fn_id_of(blob: bytes) -> bytes:
    """Stable function id = content hash of the pickled function
    (reference: function table keys are function hashes)."""
    import hashlib
    return hashlib.blake2b(blob, digest_size=16).digest()


class RemoteFunction:
    def __init__(self, fn, **default_options):
        self._fn = fn
        self._options = default_options
        self._fn_blob: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        functools.update_wrapper(self, fn)

    def options(self, **options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(options)
        rf = RemoteFunction(self._fn, **merged)
        rf._fn_blob = self._fn_blob
        rf._fn_id = self._fn_id
        return rf

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__!r} cannot be called "
            "directly; use .remote()")

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        rt = _require_runtime()
        opts = self._options
        if self._fn_blob is None:
            self._fn_blob = serialization.dumps_control(self._fn)
            self._fn_id = _fn_id_of(self._fn_blob)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        task_id = _next_task_id()
        return_ids = [] if streaming else [
            ObjectID.of(task_id, i) for i in range(num_returns)]
        strategy, pg, bundle = _normalize_strategy(opts)
        resources = task_resources(opts.get("num_cpus"), opts.get("num_tpus"),
                                   opts.get("memory"), opts.get("resources"),
                                   default_num_cpus=1.0)
        nested: List[ObjectID] = []
        spec = TaskSpec(
            task_id=task_id,
            name=opts.get("name") or self._fn.__name__,
            fn_blob=self._fn_blob, method_name=None,
            arg_descs=[_pack_arg(a, nested) for a in args],
            kwarg_descs={k: _pack_arg(v, nested)
                         for k, v in kwargs.items()},
            nested_refs=tuple(nested),
            return_ids=return_ids, resources=resources,
            max_retries=0 if streaming else opts.get(
                "max_retries", Config.get("task_max_retries_default")),
            placement_group=pg, bundle_index=bundle,
            scheduling_strategy=strategy,
            runtime_env=_prepare_env(opts.get("runtime_env")),
            streaming=streaming, fn_id=self._fn_id,
            trace_ctx=_tracing.submit_span(
                opts.get("name") or self._fn.__name__, task_id.hex())
            if (_tracing._enabled or _tracing.current() is not None)
            else None)
        rt.submit_spec(spec)
        if streaming:
            return ObjectRefGenerator(task_id)
        refs = [ObjectRef(oid) for oid in return_ids]
        return refs[0] if num_returns == 1 else refs


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: Any = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._qual: Optional[str] = None   # "Cls.method", built on first use

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(self._handle, self._name,
                           opts.get("num_returns", self._num_returns))

    def remote(self, *args, **kwargs):
        qual = self._qual
        if qual is None:
            qual = self._qual = \
                f"{self._handle._class_name}.{self._name}"
        return _submit_actor_task(
            self._handle, method_name=self._name, fn_blob=None,
            args=args, kwargs=kwargs, num_returns=self._num_returns,
            qual=qual)

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this method call (reference: dag/dag_node.py —
        actor_method.bind builds a ClassMethodNode)."""
        from ray_tpu.dag import ClassMethodNode
        return ClassMethodNode(self._handle, self._name, args, kwargs)


def _submit_actor_task(handle: "ActorHandle", *, method_name, fn_blob,
                       args, kwargs, num_returns: Any, qual=None):
    """Shared submit path for actor methods and __ray_call__ applies.
    ``num_returns="streaming"`` runs a generator method: yielded items
    publish one-by-one and the caller gets an ObjectRefGenerator
    (reference: streaming actor calls via ObjectRefStream).

    Plain method calls with inline args take the direct fast path
    (reference: actor_task_submitter.h:68 caller->actor push): the frame
    goes straight to the bound worker, skipping spec/events/scheduling."""
    rt = _require_runtime()
    streaming = num_returns == "streaming"
    task_id = TaskID.of(handle._actor_id)
    if streaming:
        return_ids = []
    elif num_returns == 1:
        return_ids = [ObjectID.of(task_id, 0)]
    else:
        return_ids = [ObjectID.of(task_id, i) for i in range(num_returns)]
    nested: List[ObjectID] = []
    arg_descs = [_pack_arg(a, nested) for a in args] if args else []
    kwarg_descs = {k: _pack_arg(v, nested)
                   for k, v in kwargs.items()} if kwargs else {}
    if qual is None:
        qual = f"{handle._class_name}.{method_name or '__ray_call__'}"
    tracing_on = _tracing._enabled or _tracing.current() is not None
    if (not streaming and method_name is not None and not tracing_on
            and not nested
            and isinstance(rt, _rtmod.Runtime)
            and all(d[0] == "val" for d in arg_descs)
            and all(d[0] == "val" for d in kwarg_descs.values())):
        if rt.submit_actor_direct(
                handle._actor_id, task_id, qual, method_name,
                return_ids,
                [("inline", p) for _t, p in arg_descs],
                {k: ("inline", p) for k, (_t, p) in kwarg_descs.items()},
                handle._max_concurrency):
            refs = [ObjectRef(oid) for oid in return_ids]
            return refs[0] if num_returns == 1 else refs
    elif ((method_name is not None or fn_blob is not None)
          and not tracing_on and not nested
          and all(d[0] == "val" for d in arg_descs)
          and all(d[0] == "val" for d in kwarg_descs.values())
          and _rtmod._worker_runtime is not None
          and rt is _rtmod._worker_runtime
          and hasattr(rt, "submit_actor_direct")):
        # Worker caller: push over this process's direct channel to the
        # actor's worker (direct.py) — the head never sees the call.
        # Ref args fall back to the classic path: only the head's
        # dep-retention keeps the argument objects alive for the task's
        # lifetime (reference: task-arg pinning in reference_counter.h).
        wire_args = [("inline", p) for _t, p in arg_descs]
        wire_kwargs = {k: ("inline", p)
                       for k, (_t, p) in kwarg_descs.items()}
        if rt.submit_actor_direct(
                handle._actor_id, task_id, qual,
                method_name, return_ids, wire_args, wire_kwargs,
                handle._max_concurrency, streaming, fn_blob=fn_blob):
            if streaming:
                return ObjectRefGenerator(task_id)
            refs = [ObjectRef(oid) for oid in return_ids]
            return refs[0] if num_returns == 1 else refs
    spec = TaskSpec(
        task_id=task_id,
        name=qual,
        fn_blob=fn_blob, method_name=method_name,
        arg_descs=arg_descs, kwarg_descs=kwarg_descs,
        nested_refs=tuple(nested),
        return_ids=return_ids, resources=ResourceSet(),
        actor_id=handle._actor_id,
        max_concurrency=handle._max_concurrency,
        streaming=streaming,
        trace_ctx=_tracing.submit_span(qual, task_id.hex())
        if tracing_on else None)
    rt.submit_spec(spec)
    if streaming:
        return ObjectRefGenerator(task_id)
    refs = [ObjectRef(oid) for oid in return_ids]
    return refs[0] if num_returns == 1 else refs


class _RayCallMethod:
    """``actor.__ray_call__.remote(fn, *args)`` runs fn(instance, *args) on
    the actor's worker (reference: ActorHandle.__ray_call__)."""

    def __init__(self, handle: "ActorHandle"):
        self._handle = handle

    def remote(self, fn, *args, **kwargs) -> "ObjectRef":
        return _submit_actor_task(
            self._handle, method_name=None,
            fn_blob=serialization.dumps_control(fn),
            args=args, kwargs=kwargs, num_returns=1)


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "",
                 max_concurrency: int = 1):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_concurrency = max_concurrency

    def __getattr__(self, name: str) -> ActorMethod:
        if name == "__ray_call__":
            return _RayCallMethod(self)
        if name.startswith("_"):
            raise AttributeError(name)
        # Memoize: the hot loop `handle.m.remote()` must not allocate a
        # fresh ActorMethod per call.  Instance-dict entries win over
        # __getattr__, so this runs once per (handle, method).
        m = ActorMethod(self, name)
        self.__dict__[name] = m
        return m

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._class_name, self._max_concurrency))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"


class ActorClass:
    def __init__(self, cls, **default_options):
        self._cls = cls
        self._options = default_options
        self._cls_blob: Optional[bytes] = None

    def options(self, **options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(options)
        ac = ActorClass(self._cls, **merged)
        ac._cls_blob = self._cls_blob
        return ac

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            "directly; use .remote()")

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = _require_runtime()
        opts = self._options
        name = opts.get("name")
        if name and opts.get("get_if_exists"):
            existing = _control("get_named_actor", name,
                                opts.get("namespace"))
            if existing is not None:
                aid, _mr, cls_name = existing
                return ActorHandle(ActorID(aid), cls_name,
                                   opts.get("max_concurrency", 1))
        if self._cls_blob is None:
            self._cls_blob = serialization.dumps_control(self._cls)
        actor_id = ActorID.of(rt.job_id)
        max_restarts = opts.get("max_restarts",
                                Config.get("actor_max_restarts_default"))
        _control("register_actor", actor_id.binary(), name,
                 opts.get("namespace"), max_restarts, self._cls.__name__)
        strategy, pg, bundle = _normalize_strategy(opts)
        resources = task_resources(opts.get("num_cpus"), opts.get("num_tpus"),
                                   opts.get("memory"), opts.get("resources"),
                                   default_num_cpus=0.0)
        nested: List[ObjectID] = []
        spec = TaskSpec(
            task_id=TaskID.of(actor_id),
            name=f"{self._cls.__name__}.__init__",
            fn_blob=self._cls_blob, method_name=None,
            arg_descs=[_pack_arg(a, nested) for a in args],
            kwarg_descs={k: _pack_arg(v, nested)
                         for k, v in kwargs.items()},
            nested_refs=tuple(nested),
            return_ids=[], resources=resources,
            create_actor_id=actor_id,
            placement_group=pg, bundle_index=bundle,
            scheduling_strategy=strategy,
            runtime_env=_prepare_env(opts.get("runtime_env")),
            max_concurrency=opts.get("max_concurrency", 1))
        _control("actor_creation_spec", actor_id.binary(), spec)
        rt.submit_spec(spec)
        return ActorHandle(actor_id, self._cls.__name__,
                           opts.get("max_concurrency", 1))


def remote(*args, **options):
    """``@remote`` / ``@remote(**options)`` for functions and classes."""
    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)
    if len(args) == 1 and not options and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("remote() takes keyword options only")
    return wrap


# --------------------------------------------------------------------- #
# module-level API
# --------------------------------------------------------------------- #

def get(refs, timeout: Optional[float] = None):
    rt = _require_runtime()
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef, got {type(r).__name__}")
    values = rt.get([r.id() for r in ref_list], timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    return ObjectRef(_put_value(value))


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    rt = _require_runtime()
    ids = [r.id() for r in refs]
    ready_ids, pending_ids = rt.wait(ids, num_returns, timeout, fetch_local)
    by_id = {r.id(): r for r in refs}
    return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _control("kill_actor", actor._actor_id.binary(), no_restart)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    found = _control("get_named_actor", name, namespace)
    if found is None:
        raise ValueError(f"no actor named {name!r}")
    aid, _mr, cls_name = found
    return ActorHandle(ActorID(aid), cls_name)


def cluster_resources() -> Dict[str, float]:
    return _control("cluster_resources")


def available_resources() -> Dict[str, float]:
    return _control("available_resources")


def nodes() -> List[Dict[str, Any]]:
    return _control("nodes")


# --------------------------------------------------------------------- #
# placement groups (reference: python/ray/util/placement_group.py)
# --------------------------------------------------------------------- #

class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundle_count: int = 0):
        self.id = pg_id
        self.bundle_count = bundle_count

    def ready(self, timeout: Optional[float] = 30.0) -> bool:
        deadline = None if timeout is None else (timeout + _mono())
        while True:
            state = _control("pg_state", self.id.binary())
            if state == "CREATED":
                return True
            if state in ("REMOVED", None):
                return False
            if deadline is not None and _mono() > deadline:
                return False
            import time
            time.sleep(0.01)

    def bundle_locations(self):
        return _control("pg_bundle_locations", self.id.binary())

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_count))


def _mono() -> float:
    import time
    return time.monotonic()


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: Optional[str] = None) -> PlacementGroup:
    pg_id_bytes = _control("create_pg", bundles, strategy, name)
    return PlacementGroup(PlacementGroupID(pg_id_bytes), len(bundles))


def remove_placement_group(pg: PlacementGroup) -> None:
    _control("remove_pg", pg.id.binary())
