"""Workflows are deprecated, matching the reference tombstone
(reference: python/ray/workflow/__init__.py — 4 LoC)."""

raise ImportError(
    "ray_tpu.workflow has been deprecated, mirroring Ray's removal of the "
    "workflow library. Use tasks + actors with checkpointing instead.")
