"""Pipeline-parallelism tests on the virtual CPU mesh (conftest forces 8
devices).  Reference analog: none in-repo (the reference delegates PP to
vLLM, llm/_internal/common/placement.py:47); tested here like the other
native parallelism strategies (ring/ulysses)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import LlamaConfig
from ray_tpu.models.llama import init_params
from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.parallel.spmd import make_lm_eval_step, make_lm_train_step

BASE = dict(vocab_size=256, hidden=64, layers=4, heads=8, kv_heads=8,
            head_dim=16, mlp_dim=128, max_seq_len=64, dtype=jnp.float32,
            attention_impl="reference")


def _tokens(batch=8, seq=64, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 256, (batch, seq), dtype=np.int32))


class TestPipelineParallel:
    def test_matches_no_pp_forward(self):
        mesh = build_mesh(MeshSpec(dp=2, tp=2, pp=2))
        params = init_params(LlamaConfig(**BASE), jax.random.key(0))
        tokens = _tokens()
        l_pp = float(make_lm_eval_step(
            LlamaConfig(**BASE, remat=False, pp_microbatches=4), mesh)(
                params, {"tokens": tokens}))
        l_np = float(make_lm_eval_step(
            LlamaConfig(**BASE, remat=False), mesh)(
                params, {"tokens": tokens}))
        assert abs(l_pp - l_np) < 1e-4

    @pytest.mark.parametrize("pp,dp,tp", [(2, 2, 2), (4, 2, 1)])
    def test_trains_to_decreasing_loss(self, pp, dp, tp):
        mesh = build_mesh(MeshSpec(dp=dp, tp=tp, pp=pp))
        cfg = LlamaConfig(**BASE, remat=False, pp_microbatches=4)
        init_fn, step_fn, place = make_lm_train_step(cfg, mesh,
                                                     learning_rate=1e-3)
        params, opt = init_fn(jax.random.key(0))
        batch = place({"tokens": _tokens()})
        losses = []
        for _ in range(5):
            params, opt, m = step_fn(params, opt, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_pp_with_remat(self):
        mesh = build_mesh(MeshSpec(dp=4, pp=2))
        cfg = LlamaConfig(**BASE, remat=True, pp_microbatches=2)
        init_fn, step_fn, place = make_lm_train_step(cfg, mesh,
                                                     learning_rate=1e-3)
        params, opt = init_fn(jax.random.key(0))
        batch = place({"tokens": _tokens()})
        for _ in range(2):
            params, opt, m = step_fn(params, opt, batch)
        assert np.isfinite(float(m["loss"]))

    def test_layer_params_sharded_over_pp(self):
        mesh = build_mesh(MeshSpec(dp=4, pp=2))
        cfg = LlamaConfig(**BASE, remat=False, pp_microbatches=2)
        init_fn, _, _ = make_lm_train_step(cfg, mesh, learning_rate=1e-3)
        params, _ = init_fn(jax.random.key(0))
        spec = params["blocks"]["wq"].sharding.spec
        assert spec[0] == "pp"

    def test_pp_requires_mesh(self):
        from ray_tpu.parallel.mesh import set_global_mesh
        from ray_tpu.models.llama import loss_fn
        set_global_mesh(None)
        cfg = LlamaConfig(**BASE, pp_microbatches=2)
        with pytest.raises(ValueError, match="pp"):
            loss_fn(init_params(cfg, jax.random.key(0)),
                    {"tokens": _tokens(2)}, cfg)
