"""JobManager + JobSupervisor: entrypoint subprocesses supervised by actors.

Reference: dashboard/modules/job/job_manager.py:58 (JobManager — submit,
monitor loop, status bookkeeping in GCS KV) and job_supervisor.py:57
(JobSupervisor actor — spawns the entrypoint shell command in a subprocess,
streams logs to a file, reports the exit code).
"""

from __future__ import annotations

import os
import string
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "submission_id": self.submission_id,
            "entrypoint": self.entrypoint, "status": self.status,
            "message": self.message, "start_time": self.start_time,
            "end_time": self.end_time, "metadata": self.metadata,
        }


class _JobSupervisor:
    """Actor supervising one entrypoint subprocess (reference:
    job_supervisor.py:57).  The subprocess starts in __init__ so status
    polls are never blocked behind a long-running call."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]], log_path: str):
        import subprocess

        self.submission_id = submission_id
        self.log_path = log_path
        env = dict(os.environ)
        # The job's driver process must not inherit this worker's runtime
        # wiring; it creates its own ray_tpu session.
        for k in list(env):
            if k.startswith("RAY_TPU_WORKER"):
                env.pop(k)
        env["RAY_TPU_JOB_SUBMISSION_ID"] = submission_id
        if env_vars:
            env.update(env_vars)
        self._log_f = open(log_path, "wb")
        self.proc = subprocess.Popen(
            entrypoint, shell=True, stdout=self._log_f,
            stderr=subprocess.STDOUT, env=env,
            start_new_session=True)  # own process group for clean stop

    def poll(self) -> Optional[int]:
        """None while running, else the exit code."""
        code = self.proc.poll()
        if code is not None:
            self._log_f.flush()
        return code

    def stop(self) -> bool:
        import signal

        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            deadline = time.monotonic() + 3.0
            while self.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if self.proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
                self.proc.wait()
            return True
        return False

    def logs(self) -> bytes:
        self._log_f.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return b""


_ALLOWED_ID = set(string.ascii_letters + string.digits + "-_")


class JobManager:
    """Tracks supervised jobs on the head (reference: job_manager.py:58)."""

    def __init__(self, log_dir: Optional[str] = None):
        self._jobs: Dict[str, JobInfo] = {}
        self._supervisors: Dict[str, Any] = {}
        self.log_dir = log_dir or os.path.join(
            "/tmp/ray_tpu", "job_logs", str(os.getpid()))
        os.makedirs(self.log_dir, exist_ok=True)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if set(submission_id) - _ALLOWED_ID:
            raise ValueError(f"invalid submission_id {submission_id!r}")
        if submission_id in self._jobs:
            raise ValueError(f"job {submission_id!r} already exists")
        info = JobInfo(submission_id, entrypoint,
                       runtime_env=runtime_env, metadata=metadata or {})
        env_vars = (runtime_env or {}).get("env_vars")
        log_path = os.path.join(self.log_dir, f"{submission_id}.log")
        supervisor = ray_tpu.remote(_JobSupervisor).options(
            name=f"_job_supervisor:{submission_id}",
            num_cpus=0).remote(submission_id, entrypoint, env_vars, log_path)
        self._jobs[submission_id] = info
        self._supervisors[submission_id] = supervisor
        info.status = JobStatus.RUNNING
        return submission_id

    def _refresh(self, submission_id: str) -> JobInfo:
        info = self._jobs[submission_id]
        if info.status in JobStatus.TERMINAL:
            self._reap_supervisor(submission_id)
            return info
        sup = self._supervisors.get(submission_id)
        if sup is None:
            info.status = JobStatus.FAILED
            info.message = "supervisor gone"
            info.end_time = time.time()
            return info
        try:
            code = ray_tpu.get(sup.poll.remote(), timeout=30)
        except Exception as e:
            info.status = JobStatus.FAILED
            info.message = f"supervisor died: {e!r}"
            info.end_time = time.time()
            self._reap_supervisor(submission_id)
            return info
        if code is None:
            return info
        info.end_time = time.time()
        if code == 0:
            info.status = JobStatus.SUCCEEDED
        else:
            info.status = JobStatus.FAILED
            info.message = f"entrypoint exited with code {code}"
        # Terminal: the supervisor actor has nothing left to supervise —
        # without this reap every submitted job leaks one named actor
        # (and its worker process) for the rest of the session (found by
        # the leak sanitizer).  Logs stay readable from the head-local
        # log file.
        self._reap_supervisor(submission_id)
        return info

    def _reap_supervisor(self, submission_id: str) -> None:
        sup = self._supervisors.pop(submission_id, None)
        if sup is None:
            return
        # Pull the log bytes down BEFORE the kill: on a multi-node
        # cluster the supervisor wrote its log file on ITS node, so the
        # head-local fallback in get_job_logs would otherwise read
        # nothing once the actor is gone.
        log_path = os.path.join(self.log_dir, f"{submission_id}.log")
        try:
            if not os.path.exists(log_path):
                data = ray_tpu.get(sup.logs.remote(), timeout=30)
                with open(log_path, "wb") as f:
                    f.write(data)
        except Exception:
            pass  # dead supervisor: whatever is on disk is all there is
        try:
            ray_tpu.kill(sup)
        except Exception:
            pass  # actor already dead / runtime tearing down

    def get_job_status(self, submission_id: str) -> str:
        return self._refresh(submission_id).status

    def get_job_info(self, submission_id: str) -> JobInfo:
        return self._refresh(submission_id)

    def list_jobs(self) -> List[JobInfo]:
        return [self._refresh(sid) for sid in list(self._jobs)]

    def stop_job(self, submission_id: str) -> bool:
        info = self._refresh(submission_id)
        if info.status in JobStatus.TERMINAL:
            return False
        stopped = ray_tpu.get(
            self._supervisors[submission_id].stop.remote(), timeout=30)
        info.status = JobStatus.STOPPED
        info.end_time = time.time()
        self._reap_supervisor(submission_id)
        return bool(stopped)

    def get_job_logs(self, submission_id: str) -> str:
        if submission_id not in self._jobs:
            raise KeyError(submission_id)
        sup = self._supervisors.get(submission_id)
        if sup is None:
            # Supervisor reaped at job end: the log file on the head is
            # the durable copy.
            log_path = os.path.join(self.log_dir, f"{submission_id}.log")
            try:
                with open(log_path, "rb") as f:
                    return f.read().decode(errors="replace")
            except FileNotFoundError:
                return ""
        data = ray_tpu.get(sup.logs.remote(), timeout=30)
        return data.decode(errors="replace")

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")
