"""Runtime env tests: working_dir / py_modules packaging + worker
application, dashboard HTTP surface (reference analogs:
python/ray/tests/test_runtime_env_working_dir*.py, dashboard tests)."""

from __future__ import annotations

import json
import os
import tempfile
import urllib.request

import pytest

import ray_tpu


@pytest.fixture()
def rt():
    runtime = ray_tpu.init(num_cpus=4)
    yield runtime
    ray_tpu.shutdown()


class TestRuntimeEnv:
    def test_working_dir_ships_files(self, rt):
        d = tempfile.mkdtemp(prefix="wd_")
        with open(os.path.join(d, "payload.txt"), "w") as f:
            f.write("hello from working_dir")

        @ray_tpu.remote(runtime_env={"working_dir": d})
        def read_payload():
            # Worker chdir'd into the extracted package.
            with open("payload.txt") as f:
                return f.read()

        assert ray_tpu.get(read_payload.remote(),
                           timeout=60) == "hello from working_dir"

    def test_py_modules_importable(self, rt):
        d = tempfile.mkdtemp(prefix="mod_")
        os.makedirs(os.path.join(d, "shipped_pkg"))
        with open(os.path.join(d, "shipped_pkg", "__init__.py"), "w") as f:
            f.write("MAGIC = 1234\n")

        @ray_tpu.remote(runtime_env={"py_modules": [d]})
        def use_module():
            import shipped_pkg
            return shipped_pkg.MAGIC

        assert ray_tpu.get(use_module.remote(), timeout=60) == 1234

    def test_working_dir_actor(self, rt):
        d = tempfile.mkdtemp(prefix="wda_")
        with open(os.path.join(d, "conf.json"), "w") as f:
            json.dump({"x": 7}, f)

        @ray_tpu.remote(runtime_env={"working_dir": d})
        class Reader:
            def __init__(self):
                with open("conf.json") as f:
                    self.conf = json.load(f)

            def x(self):
                return self.conf["x"]

        a = Reader.remote()
        assert ray_tpu.get(a.x.remote(), timeout=60) == 7
        ray_tpu.kill(a)

    def test_conda_rejected_clearly(self, rt):
        with pytest.raises(NotImplementedError, match="conda"):
            @ray_tpu.remote(runtime_env={"conda": "myenv"})
            def f():
                return 1
            f.remote()

    def test_pip_env_installs_local_package(self, rt, tmp_path):
        """pip runtime env: worker runs under a venv layering a local
        package over the system site-packages (reference:
        runtime_env/pip.py; --no-index keeps it offline-safe)."""
        pkg = tmp_path / "tinypkg"
        (pkg / "tinypkg_rtenv").mkdir(parents=True)
        (pkg / "tinypkg_rtenv" / "__init__.py").write_text(
            "MAGIC = 'pip-env-works'\n")
        (pkg / "pyproject.toml").write_text(
            '[project]\nname = "tinypkg-rtenv"\nversion = "0.1"\n'
            '[build-system]\nrequires = ["setuptools"]\n'
            'build-backend = "setuptools.build_meta"\n'
            '[tool.setuptools]\npackages = ["tinypkg_rtenv"]\n')

        @ray_tpu.remote(runtime_env={"pip": [
            "--no-index", "--no-build-isolation", str(pkg)]})
        def probe():
            import sys

            import tinypkg_rtenv
            return tinypkg_rtenv.MAGIC, sys.executable

        magic, exe = ray_tpu.get(probe.remote(), timeout=600)
        assert magic == "pip-env-works"
        assert "venv_" in exe  # ran under the env's interpreter

        # The package must NOT leak into plain workers.
        @ray_tpu.remote
        def plain():
            try:
                import tinypkg_rtenv  # noqa: F401
                return "leaked"
            except ImportError:
                return "clean"

        assert ray_tpu.get(plain.remote(), timeout=60) == "clean"

    def test_pip_dict_form_and_unknown_keys(self, rt):
        from ray_tpu._private.runtime_env import prepare_runtime_env
        out = prepare_runtime_env({"pip": {"packages": ["a", "b"]}})
        assert out["pip"] == ["a", "b"]
        with pytest.raises(NotImplementedError, match="env_overrides"):
            prepare_runtime_env({"pip": {"env_overrides": {}}})

    def test_pip_local_path_edit_invalidates_cache(self, rt, tmp_path):
        """Editing a local-path requirement must change the venv signature
        (stale cached envs would silently run old code)."""
        from ray_tpu._private.runtime_env import pip_env_signature
        pkg = tmp_path / "p"
        pkg.mkdir()
        (pkg / "f.py").write_text("x = 1\n")
        s1 = pip_env_signature(["--no-index", str(pkg)])
        import time as _t
        _t.sleep(0.01)
        (pkg / "f.py").write_text("x = 2\n")
        s2 = pip_env_signature(["--no-index", str(pkg)])
        assert s1 != s2

    def test_pip_env_failure_surfaces(self, rt):
        @ray_tpu.remote(runtime_env={"pip": [
            "--no-index", "definitely-not-a-real-package-xyz"]})
        def f():
            return 1

        with pytest.raises(Exception, match="pip runtime_env setup failed"):
            ray_tpu.get(f.remote(), timeout=600)

    def test_missing_dir_raises(self, rt):
        with pytest.raises(ValueError, match="not found"):
            @ray_tpu.remote(runtime_env={"working_dir": "/no/such/dir"})
            def f():
                return 1
            f.remote()


class TestDashboard:
    def test_endpoints(self, rt):
        from ray_tpu.dashboard import start_dashboard

        @ray_tpu.remote
        def noop():
            return 1
        ray_tpu.get([noop.remote() for _ in range(3)])

        dash = start_dashboard(port=0)
        base = f"http://127.0.0.1:{dash.port}"

        def get_json(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.loads(r.read())

        cluster = get_json("/api/cluster")
        assert cluster["total_resources"].get("CPU") == 4.0
        nodes = get_json("/api/nodes")
        assert len(nodes) == 1 and nodes[0]["is_head"]
        summary = get_json("/api/tasks/summary")
        assert "noop" in summary
        assert get_json("/api/jobs")
        with urllib.request.urlopen(base + "/-/healthz", timeout=10) as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(base + "/", timeout=10) as r:
            assert b"ray_tpu" in r.read()
        dash.stop()
