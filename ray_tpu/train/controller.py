"""Train controller: worker group lifecycle, failure handling, reports.

Clone of the reference's Train v2 control loop (reference:
python/ray/train/v2/_internal/execution/controller/controller.py:103, loop
:682,739 — poll worker group, consult failure policy, restart from latest
checkpoint) with the torch/NCCL backend swapped for jax.distributed world
formation (reference: train/v2/jax/config.py:40 _JaxBackend — rank-0
address broadcast, per-worker env, jax.distributed.initialize, MEGASCALE
multi-slice env plumbing :95-103).
"""

from __future__ import annotations

import pickle
import socket
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .._private import serialization
from ._checkpoint import Checkpoint, CheckpointManager
from ._context import drain_ack_prefix, drain_key


class CrashLoopError(RuntimeError):
    """The same error signature recurred immediately N times: restarting
    will not fix a deterministic crash.  Raised (as ``Result.error``) by
    the crash-loop circuit breaker with the diagnosis bundle path."""

    def __init__(self, signature: str, count: int,
                 last_error: Optional[BaseException] = None,
                 bundle_path: Optional[str] = None):
        super().__init__(
            f"crash loop: {count} consecutive restarts died with the "
            f"same signature [{signature}]"
            + (f"; diagnosis bundle: {bundle_path}" if bundle_path
               else ""))
        self.signature = signature
        self.count = count
        self.last_error = last_error
        self.bundle_path = bundle_path


def _error_signature(exc: BaseException) -> str:
    """Stable identity of a failure for crash-loop detection: type plus
    the first line of the message (line numbers / object ids in later
    lines would make every recurrence look 'different')."""
    first = str(exc).splitlines()[0] if str(exc) else ""
    return f"{type(exc).__name__}: {first[:200]}"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TrainWorker:
    """Actor hosting one training process (reference:
    train/v2/_internal/execution/worker_group/worker.py:124)."""

    def __init__(self, rank: int, world_size: int, run_id: str):
        self.rank = rank
        self.world_size = world_size
        self.run_id = run_id
        self._dist_initialized = False

    def setup_dist(self, coordinator_addr: str,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> bool:
        """Form the jax.distributed world (gloo on CPU, ICI/DCN on TPU).

        ``num_processes``/``process_id`` override the global rank/world for
        slice-local worlds: in multi-slice mode each slice is its own
        jax.distributed world and the cross-slice (DCN) axis is handled
        above it (reference: train/v2/jax/config.py:95-133 — per-slice
        coordinators + MEGASCALE env for the inter-slice fabric)."""
        import os

        import jax
        if "tpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
        jax.distributed.initialize(
            coordinator_addr,
            num_processes=self.world_size if num_processes is None
            else num_processes,
            process_id=self.rank if process_id is None else process_id)
        self._dist_initialized = True
        return True

    def run(self, fn_blob: bytes, config: Optional[Dict[str, Any]],
            ctx_info: Dict[str, Any]) -> str:
        import os

        from . import _context
        ctx = _context.TrainContext(
            run_id=self.run_id, rank=self.rank,
            world_size=self.world_size, local_rank=self.rank,
            storage_path=ctx_info["storage_path"],
            experiment_name=ctx_info["experiment_name"],
            latest_checkpoint=ctx_info.get("latest_checkpoint"),
            slice_id=int(os.environ.get(
                "MEGASCALE_SLICE_ID", ctx_info.get("slice_id", 0))),
            num_slices=ctx_info.get("num_slices", 1),
            checkpoint_options=ctx_info.get("checkpoint"),
            mesh_info=ctx_info.get("mesh"))
        _context.set_context(ctx)
        try:
            fn = serialization.loads_control(fn_blob)
            # Recompile detector: shape churn in the user's jitted step
            # fn is the #1 silent TPU step-time regression — every train
            # worker watches for it by default
            # (RAY_TPU_RECOMPILE_DETECT=0 opts out).  install() only
            # engages once jax is imported, so it runs AFTER the train
            # fn deserialized (unpickling restores the fn's module
            # imports, incl. jax) and after any setup_dist import;
            # fns that only import jax lazily inside their body wrap
            # explicitly with ray_tpu.profiler.track().
            if os.environ.get("RAY_TPU_RECOMPILE_DETECT", "1") != "0":
                from ..profiler import recompile
                recompile.install()
            if config is not None:
                fn(config)
            else:
                fn()
            # Drain the async checkpoint writer BEFORE reporting success:
            # every submitted save must have published + acked (or raised)
            # by the time the controller sees this rank finish.
            ctx.teardown()
            return "ok"
        finally:
            _context.set_context(None)

    def shutdown_dist(self) -> bool:
        if self._dist_initialized:
            try:
                import jax
                jax.distributed.shutdown()
            except Exception:
                pass
        return True

    def ping(self) -> str:
        return "pong"


@dataclass
class WorkerGroupState:
    workers: List[Any] = field(default_factory=list)  # ActorHandles
    run_refs: List[Any] = field(default_factory=list)


class TrainController:
    """Drives the worker group to completion (runs in the driver)."""

    def __init__(self, train_fn: Callable, train_loop_config,
                 scaling_config, run_config):
        from .scaling_policy import make_scaling_policy
        self.train_fn = train_fn
        self.train_loop_config = train_loop_config
        self.scaling = scaling_config
        self.run_config = run_config
        self.run_id = uuid.uuid4().hex[:12]
        # Fail fast on a mesh no configured world size can tile (the
        # sizing error belongs at fit(), not one group-formation later).
        self.mesh_config = getattr(scaling_config, "mesh_config", None)
        if self.mesh_config is not None:
            self.mesh_config.validate_scaling(scaling_config)
        #: Mesh axis sizes of the current incarnation (Result.mesh; a
        #: change between incarnations is a mesh reshape).
        self._mesh_axes: Optional[Dict[str, int]] = None
        self.policy = make_scaling_policy(scaling_config)
        self.manager = CheckpointManager(
            run_config.storage_path, run_config.name,
            num_to_keep=run_config.checkpoint_config.num_to_keep)
        self._reports: List[Dict[str, Any]] = []
        self._seen_report_keys: set = set()
        self._seen_ack_keys: set = set()
        # Rank-0 step-phase attribution totals (seconds per phase) from
        # the report stream — Result.step_phases.
        self._phase_totals: Dict[str, float] = {}
        # Goodput accounting (reference analog: MegaScale-style wall-time
        # partitioning): init/step/checkpoint/restart/idle phases; the
        # ratio lands on the ray_tpu_train_goodput_ratio gauge live.
        from ..util.telemetry import GoodputTracker
        self.goodput = GoodputTracker(initial_phase="init")
        # Hang/straggler watchdog over the per-rank report stream
        # (watchdog.py); fed from _poll_reports, polled on its own thread.
        from .watchdog import TrainWatchdog
        self.watchdog = TrainWatchdog(
            self.run_id, getattr(run_config, "watchdog", None))
        # Drain protocol / restart-hardening state.
        self._last_drain_poll_mono = 0.0
        # Monotonic stamp of the newest durable checkpoint (manifest
        # commit or legacy dir registration): the failure path books
        # "lost" from here, not from group start.
        self._last_ckpt_mono = 0.0
        self.num_drains = 0
        self._failure_times: "deque[float]" = deque()
        self._last_error_sig: Optional[str] = None
        self._crash_streak = 0

    # -- worker group -------------------------------------------------------

    def _worker_env(self, rank: int, world: int) -> Dict[str, str]:
        env: Dict[str, str] = dict(self.scaling.env_per_worker or {})
        if not self.scaling.use_tpu:
            env.setdefault("JAX_PLATFORMS", "cpu")
            env.setdefault("PALLAS_AXON_POOL_IPS", "")
            env.setdefault("XLA_FLAGS", "")
            dpw = self.mesh_config.devices_per_worker \
                if self.mesh_config is not None else 1
            if dpw > 1:
                # Multi-device worker processes on the CPU substrate:
                # force XLA host-platform devices so tier-1 and the
                # bench exercise REAL multi-device meshes (on TPU the
                # chips-per-worker resource grant does this instead).
                from .mesh.runtime import xla_host_device_flags
                env["XLA_FLAGS"] = xla_host_device_flags(
                    env.get("XLA_FLAGS"), dpw)
        if self.scaling.num_slices > 1:
            from ..accelerators.tpu import get_tpu_coordinator_env_vars
            # Slice layout follows the ACTUAL group size (elastic groups
            # may be smaller than the configured num_workers).
            workers_per_slice = max(1, world // self.scaling.num_slices)
            env.update(get_tpu_coordinator_env_vars(
                slice_id=rank // workers_per_slice,
                num_slices=self.scaling.num_slices,
                coordinator_address=self._megascale_addr))
        return env

    def _devices_per_worker(self) -> int:
        if self.mesh_config is not None:
            return self.mesh_config.devices_per_worker
        # No mesh config: TPU workers still own chips_per_worker chips
        # (the status/Result display must not undercount them).
        if self.scaling.use_tpu and self.scaling.chips_per_worker:
            return self.scaling.chips_per_worker
        return 1

    def _resolved_axes(self, world: int) -> Dict[str, int]:
        """Mesh axis sizes a group of ``world`` processes forms (raises
        ValueError when the mesh cannot tile that world — callers treat
        it as a formation failure)."""
        total = world * self._devices_per_worker()
        if self.mesh_config is not None:
            spec = self.mesh_config.spec_for(total,
                                             self.scaling.num_slices)
        else:
            from ..parallel.mesh import MeshSpec
            spec = MeshSpec(dp=total)
        return {a: s for a, s in spec.shape()}

    def _valid_resize(self, target: int) -> int:
        """Snap a resize target to a world size the mesh can tile (the
        drain-to-invalid-size fix: never plan a group the MeshConfig
        cannot factor).  Falls back to ``target`` when nothing in range
        is valid — formation then fails into the failure budget."""
        if self.mesh_config is None:
            return target
        ceiling = self.scaling.max_workers or max(
            self.scaling.num_workers, target)
        v = self.mesh_config.nearest_valid_world(
            target, floor=1, ceiling=ceiling,
            num_slices=self.scaling.num_slices)
        return v if v is not None else target

    def _note_mesh_formed(self, world: int) -> None:
        """Record a SUCCESSFULLY formed group's mesh shape: axis gauges,
        the reshape counter (shape changed across incarnations), the KV
        status record `ray-tpu status` reads, and Result.mesh.  Called
        after the gang forms — a formation attempt that dies must not
        count as a reshape or publish a mesh that never existed."""
        from ..util import telemetry
        from .mesh.runtime import note_mesh_axes, publish_mesh_status
        axes = self._resolved_axes(world)
        if self._mesh_axes is not None and axes != self._mesh_axes:
            telemetry.inc("ray_tpu_train_mesh_reshapes_total")
        self._mesh_axes = axes
        note_mesh_axes(axes)
        publish_mesh_status(self.run_id, axes, world,
                            self._devices_per_worker())

    def _start_group(self, n: Optional[int] = None) -> WorkerGroupState:
        import ray_tpu

        n = n if n is not None else self.scaling.num_workers
        # The mesh must tile this world BEFORE actors spawn: a shape
        # mismatch is a formation failure here, not a cryptic per-worker
        # jax error after the gang formed.
        self._resolved_axes(n)
        self._megascale_addr = f"127.0.0.1:{_free_port()}"
        resources = dict(self.scaling.resources_per_worker or {})
        if self.scaling.use_tpu and self.scaling.chips_per_worker:
            resources["TPU"] = self.scaling.chips_per_worker

        worker_cls = ray_tpu.remote(TrainWorker)
        group = WorkerGroupState()
        for rank in range(n):
            opts: Dict[str, Any] = {
                "runtime_env": {"env_vars": self._worker_env(rank, n)},
            }
            if resources:
                opts["resources"] = resources
            group.workers.append(
                worker_cls.options(**opts).remote(rank, n, self.run_id))
        # Liveness check before dist init.
        form_t = getattr(self.scaling, "formation_timeout_s", 300.0)
        ray_tpu.get([w.ping.remote() for w in group.workers],
                    timeout=min(120.0, form_t))
        if n > 1 or self.scaling.force_distributed:
            if self.scaling.num_slices > 1 and not self.scaling.use_tpu \
                    and n % self.scaling.num_slices == 0:
                # CPU multi-slice emulation: each slice forms its own
                # jax.distributed (gloo) world; the cross-slice axis is
                # exercised by the train fn over the collective backend —
                # the DCN stand-in (reference: train/v2/jax/config.py:95,
                # per-slice coordinators).  On TPU a single world +
                # MEGASCALE env lets XLA drive the real DCN fabric.
                wps = max(1, n // self.scaling.num_slices)
                addrs = {s: f"127.0.0.1:{_free_port()}"
                         for s in range(self.scaling.num_slices)}
                ray_tpu.get([
                    w.setup_dist.remote(addrs[rank // wps],
                                        num_processes=wps,
                                        process_id=rank % wps)
                    for rank, w in enumerate(group.workers)],
                    timeout=form_t)
            else:
                addr = f"127.0.0.1:{_free_port()}"
                ray_tpu.get(
                    [w.setup_dist.remote(addr) for w in group.workers],
                    timeout=form_t)
        return group

    def _teardown_group(self, group: WorkerGroupState) -> None:
        import ray_tpu
        for w in group.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    # -- reports ------------------------------------------------------------

    def _poll_reports(self) -> None:
        from .._private.api import _control
        prefix = f"train/{self.run_id}/report/"
        for key in _control("kv_keys", prefix):
            if key in self._seen_report_keys:
                continue
            self._seen_report_keys.add(key)
            data = _control("kv_get", key)
            if data is None:
                continue
            payload = pickle.loads(data)
            self._reports.append(payload)
            self.watchdog.note_report(payload["rank"], payload["time"],
                                      payload.get("pid"),
                                      report_mono=payload.get("mono"),
                                      incarnation=payload.get("incarnation"))
            if payload["rank"] == 0:
                # Worker-measured checkpoint time happened inside what
                # the driver observes as the "step" phase: reattribute.
                self.goodput.reattribute(
                    "checkpoint", payload.get("ckpt_seconds", 0.0) or 0.0)
                phases = payload.get("phases") or {}
                for phase, seconds in phases.items():
                    if seconds > 0:
                        self._phase_totals[phase] = \
                            self._phase_totals.get(phase, 0.0) + seconds
                # Data-wait is idle devices, not productive step time:
                # an input-bound run's goodput should sag even though
                # the step loop never stops "stepping".
                self.goodput.reattribute(
                    "data_wait", phases.get("data_wait", 0.0) or 0.0)
                if payload.get("checkpoint_dir"):
                    self.manager.register(payload["checkpoint_dir"],
                                          payload["metrics"])
                    self._last_ckpt_mono = time.monotonic()
            # Consumed: GC the key (RT303) — report keys are write-once
            # per (rank, incarnation, seq); without the delete every run
            # grows the head KV forever.  The payload lives on in
            # self._reports.
            _control("kv_del", key)
        self._poll_ckpt_acks()

    def _poll_ckpt_acks(self) -> None:
        """Sharded-save commit protocol: collect per-rank shard acks and
        commit the global manifest once a step's ack set is complete (the
        coordinator half of ray_tpu.checkpoint; a crash before this
        commit leaves "latest" untouched)."""
        from .._private.api import _control
        from ..checkpoint.manager import ack_prefix
        for key in _control("kv_keys", ack_prefix(self.run_id)):
            if key in self._seen_ack_keys:
                continue
            data = _control("kv_get", key)
            if data is None:
                continue  # not marked seen: the read stays retryable
            self._seen_ack_keys.add(key)
            self.manager.note_ack(pickle.loads(data))
            # Consumed: GC the ack key (each is one (step, rank, nonce)
            # write-once record; note_ack holds the payload from here).
            _control("kv_del", key)
        if self.manager.commit_ready():
            self._last_ckpt_mono = time.monotonic()

    def _release_orphan_pins(self) -> None:
        """End-of-run sweep of ``ckpt/pin/<experiment>/*``.

        A worker killed mid-save leaves its newest blob pinned in the
        host object store with only its KV entry pointing at it — by
        design, so the NEXT incarnation chain-unpins it.  When the run
        ends there is no next incarnation: release whatever is left, or
        the blobs stay pinned (and escape-marked) for the rest of the
        session.  Live workers already released their own pins at
        train-fn teardown; this only reaps dead incarnations' leftovers
        (a leak the runtime sanitizer catches without this sweep).
        """
        from .._private.api import _control
        from ..util import telemetry
        try:
            prefix = f"ckpt/pin/{self.run_config.name}/"
            for key in _control("kv_keys", prefix):
                entry = _control("kv_get", key)
                if entry is None:
                    continue
                try:
                    ref = pickle.loads(entry).get("ref")
                except Exception:
                    ref = None
                if ref is not None:
                    _control("unpin_object", ref)
                _control("kv_del", key)
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            telemetry.note_swallowed("train.release_orphan_pins", e)

    # -- drain protocol (graceful preemption) -------------------------------

    def _poll_drain_notices(self, group: "WorkerGroupState"):
        """Check whether any live rank sits on a DRAINING node.  Returns
        ``(ranks, budget_s)`` — the covered ranks and the tightest
        remaining drain budget — or None.  Rate-limited: the node table
        scan costs a control round-trip per second, not per poll."""
        now = time.monotonic()
        if now - self._last_drain_poll_mono < 1.0:
            return None
        self._last_drain_poll_mono = now
        from .._private.api import _control
        from ..util import telemetry
        try:
            nodes = _control("nodes")
        except Exception as e:  # noqa: BLE001 — retried next poll
            telemetry.note_swallowed("train.drain_poll", e)
            return None
        draining = {n["node_id"]: n for n in nodes
                    if n.get("alive") and n.get("draining")}
        if not draining:
            return None
        try:
            actor_nodes = {a["actor_id"]: a.get("node_id")
                           for a in _control("list_actors")}
        except Exception as e:  # noqa: BLE001
            telemetry.note_swallowed("train.drain_poll", e)
            return None
        ranks = []
        covering = set()
        for rank, w in enumerate(group.workers):
            node = actor_nodes.get(w._actor_id.hex())
            if node in draining:
                ranks.append(rank)
                covering.add(node)
        if not ranks:
            return None
        budget_s = min(draining[n].get("drain_remaining_s", 0.0)
                       for n in covering)
        return ranks, max(0.5, budget_s)

    def _handle_drain(self, group: "WorkerGroupState", world: int,
                      budget_s: float, generation: int):
        """Drive the urgent-checkpoint half of a drain: publish the
        generation-tagged request, wait (bounded by the drain budget,
        minus a teardown margin) for every rank's flush ack while
        committing checkpoint acks as they land, then GC the protocol
        keys.  Returns ``(error, finished)``: a worker error if one died
        mid-drain (the caller then takes the failure path), and whether
        every rank's train fn already completed (the run is done — no
        re-formation needed)."""
        import ray_tpu

        from .._private.api import _control
        from ..util import telemetry
        telemetry.inc("ray_tpu_train_urgent_ckpt_total")
        # EVERY rank flushes (the commit needs all shards) and so every
        # rank can stall past the hang deadline — suppress verdicts for
        # the whole group, not just the draining ranks.
        self.watchdog.note_drain(range(world), budget_s + 30.0)
        wait_s = max(0.5, budget_s - 1.0)  # margin for teardown itself
        _control("kv_put", drain_key(self.run_id),
                 pickle.dumps({"generation": generation,
                               "budget_s": wait_s}))
        ack_prefix = drain_ack_prefix(self.run_id, generation)
        deadline = time.monotonic() + wait_s
        error: Optional[Exception] = None
        finished = False
        try:
            while time.monotonic() < deadline:
                self._poll_reports()  # commits ckpt acks as they land
                if len(set(_control("kv_keys", ack_prefix))) >= world:
                    break
                done_now, _ = ray_tpu.wait(
                    group.run_refs, num_returns=len(group.run_refs),
                    timeout=0.25)
                dead = False
                for ref in done_now:
                    try:
                        ray_tpu.get(ref)
                    except Exception as e:  # noqa: BLE001
                        error = e
                        dead = True
                if len(done_now) == len(group.run_refs):
                    finished = not dead
                    break
                if dead:
                    break
            # Final harvest: the last flush's shard acks may have landed
            # after the loop's poll.
            self._poll_reports()
        finally:
            # GC the ack keys (write-once per generation; RT303).  The
            # drain REQUEST key stays until after teardown — acked ranks
            # park on it ("my work is durable, take me down"), and
            # deleting it now would un-park them into manufacturing an
            # uncommitted tail.  _gc_drain_key() runs post-teardown.
            try:
                for key in _control("kv_keys", ack_prefix):
                    _control("kv_del", key)
            except Exception as e:  # noqa: BLE001 — best-effort GC
                telemetry.note_swallowed("train.drain_gc", e)
        return error, finished

    def _gc_drain_key(self) -> None:
        """Delete the drain request key once the group is gone (parked
        workers are dead; the next incarnation must not read it), and
        sweep straggler ack keys across ALL generations — a rank that
        acked after _handle_drain's deadline sweep would otherwise leak
        its key in the head KV forever (RT303 invariant)."""
        from .._private.api import _control
        from ..util import telemetry
        try:
            _control("kv_del", drain_key(self.run_id))
            for key in _control("kv_keys",
                                drain_ack_prefix(self.run_id)):
                _control("kv_del", key)
        except Exception as e:  # noqa: BLE001 — best-effort GC
            telemetry.note_swallowed("train.drain_gc", e)

    def _run_incarnation(self, group: "WorkerGroupState",
                         world: int):
        """Submit the train fn to a freshly formed group and drive it:
        poll reports/acks, watch for drain notices and elastic upsizes,
        and account lost work on failure.  Returns ``(error,
        resize_to)`` — the caller tears the group down either way."""
        import ray_tpu

        fn_blob = serialization.dumps_control(self.train_fn)
        ckpt_cfg = self.run_config.checkpoint_config
        if getattr(ckpt_cfg, "emergency_replica", False):
            # Peer RAM copy of the newest shards: spawn (or find)
            # the experiment's replica holder before workers run.
            from ..checkpoint import replica as _replica
            _replica.ensure_holder(self.run_config.name)
        ctx_info = {
            "storage_path": self.run_config.storage_path,
            "experiment_name": self.run_config.name,
            "latest_checkpoint": self.manager.latest(),
            "num_slices": self.scaling.num_slices,
            # Resolved mesh for THIS incarnation's world: workers build
            # the global mesh from it (train.get_mesh()).  The rules
            # overrides ride along so every rank shards identically.
            # Without a MeshConfig no axes are sent — the worker falls
            # back to a dp mesh over whatever devices it actually sees
            # (the controller cannot know a TPU worker's chip count).
            "mesh": {
                "axes": dict(self._mesh_axes or {})
                    if self.mesh_config is not None else {},
                "num_slices": self.scaling.num_slices,
                "devices_per_worker": self._devices_per_worker(),
                "rules": dict(self.mesh_config.rules or {})
                    if self.mesh_config is not None else {},
                "configured": self.mesh_config is not None,
            },
            "checkpoint": {
                "async_save": getattr(ckpt_cfg, "async_save", True),
                "max_inflight": getattr(ckpt_cfg, "max_inflight", 2),
                "emergency_replica": getattr(
                    ckpt_cfg, "emergency_replica", False),
                "generation": len(self.world_size_history),
            },
        }
        group.run_refs = [
            w.run.remote(fn_blob, self.train_loop_config, ctx_info)
            for w in group.workers]
        self.goodput.enter("step")
        t_step = time.monotonic()
        error = None
        resize_to: Optional[int] = None
        last_elastic_check = time.monotonic()
        pending = list(group.run_refs)
        while pending:
            done, pending = ray_tpu.wait(
                pending, num_returns=1, timeout=0.5)
            self._poll_reports()
            for ref in done:
                # A finished rank legitimately stops reporting — tell
                # the watchdog before its hang deadline can fire.
                try:
                    self.watchdog.note_done(group.run_refs.index(ref))
                except ValueError:
                    pass
                try:
                    ray_tpu.get(ref)
                except Exception as e:  # noqa: BLE001
                    error = e
                    pending = []
                    break
            # Drain notices (preemption/maintenance): a DRAINING
            # node covering live ranks triggers the graceful path —
            # urgent checkpoint flush on every rank, then a PLANNED
            # downsize before the deadline.  The preemption books
            # ~0 lost work (the resize path restores the
            # just-committed checkpoint) instead of everything
            # since the last periodic save, and burns no
            # max_failures budget.
            if pending and error is None:
                notice = self._poll_drain_notices(group)
                if notice is not None:
                    drain_ranks, budget_s = notice
                    error, finished = self._handle_drain(
                        group, world, budget_s,
                        len(self.world_size_history))
                    if error is None and not finished:
                        self.num_drains += 1
                        # Snap to a world the mesh can tile: a drain
                        # that strands an un-factorable worker count
                        # must not plan an unformable group.
                        resize_to = self._valid_resize(
                            max(1, world - len(drain_ranks)))
                    pending = []
            # Elastic upsize check (reference: elastic.py monitor
            # decision): new capacity -> teardown + re-form the world
            # at the larger size, resuming from the latest checkpoint.
            # Gated to a CHECKPOINT BOUNDARY: the reform restores from
            # the latest committed checkpoint, so re-forming before one
            # committed this incarnation would replay the whole
            # incarnation — the upsize would cost more than it buys.
            # (The interval keeps re-checking; the upsize fires at the
            # first boundary after capacity joined.)  A run that has
            # never checkpointed at all replays from the start whenever
            # the reform fires, so gating it buys nothing — it keeps
            # the pre-gate behavior and upsizes immediately.
            if pending and error is None and \
                    time.monotonic() - last_elastic_check >= \
                    self.scaling.elastic_check_interval_s and \
                    (self._last_ckpt_mono >= t_step
                     or self._last_ckpt_mono == 0.0):
                last_elastic_check = time.monotonic()
                d = self.policy.monitor_decision(len(group.workers))
                if d is not None:
                    # A crashed worker frees resources that look like
                    # growth; drain already-failed refs first so a
                    # crash takes the failure path (and max_failures
                    # accounting), not the resize path.
                    done_now, _ = ray_tpu.wait(
                        pending, num_returns=len(pending), timeout=0)
                    for ref in done_now:
                        try:
                            ray_tpu.get(ref)
                        except Exception as e:  # noqa: BLE001
                            error = e
                            break
                    if error is None:
                        resize_to = d.num_workers
                        if d.num_workers > world:
                            from ..util import telemetry
                            telemetry.inc("ray_tpu_train_upsize_total")
                    pending = []
        # Drain reports while still in the "step" phase so their
        # ckpt_seconds reattribution has step time to pull from.
        self._poll_reports()
        if error is not None:
            # Step time SINCE THE LAST COMMITTED CHECKPOINT
            # produced no surviving work (the restart replays it):
            # badput, not goodput (MegaScale-style lost-work
            # accounting).  Work up to that commit survived — it
            # must not be booked lost.
            self.goodput.reattribute(
                "lost", time.monotonic()
                - max(t_step, self._last_ckpt_mono))
        return error, resize_to

    def _trip_crash_loop(self, signature: str,
                         last_error: Exception) -> "CrashLoopError":
        """Circuit breaker tripped: capture a diagnosis bundle (error
        signature, failure history, goodput so far) and build the
        terminal error.  Forensics are best-effort — the breaker itself
        never fails."""
        from .._private.api import _control
        from ..util import telemetry
        bundle_path = None
        diagnosis = {
            "signature": signature,
            "consecutive": self._crash_streak,
            "world_size_history": list(self.world_size_history),
            "run_id": self.run_id,
            "experiment": self.run_config.name,
            "goodput": self.goodput.summary(),
        }
        try:
            _control("export_event", "EXPORT_TRAIN_WATCHDOG",
                     {"kind": "crash_loop", "run_id": self.run_id,
                      "signature": signature,
                      "consecutive": self._crash_streak})
            bundle_path = _control("debug_dump", "crash_loop", False,
                                   {"crash_loop": diagnosis})
        except Exception as e:  # noqa: BLE001 — forensics best-effort
            telemetry.note_swallowed("train.crash_loop_bundle", e)
        return CrashLoopError(signature, self._crash_streak,
                              last_error=last_error,
                              bundle_path=bundle_path)

    # -- main loop ----------------------------------------------------------

    def run(self):
        import ray_tpu

        from .trainer import Result

        failures = 0
        error: Optional[Exception] = None
        carry_target: Optional[int] = None
        self.world_size_history: List[int] = []
        self._backoff_s = \
            self.run_config.failure_config.restart_backoff_initial_s
        self.watchdog.start()
        try:
            while True:
                # First group formation is "init"; every re-formation after a
                # failure is "restart" overhead (resizes count as restart too:
                # the world re-forms and resumes from the checkpoint).
                self.goodput.enter(
                    "init" if not self.world_size_history else "restart")
                decision = self.policy.initial_decision(prefer=carry_target)
                carry_target = None
                world = decision.num_workers
                self.world_size_history.append(world)
                # Fresh incarnation: stale rank clocks must not trip on the
                # re-formed group.
                self.watchdog.reset_ranks()
                # And stale checkpoint acks from the torn-down group must
                # never complete a new incarnation's ack set (the retried
                # step re-acks under a fresh per-worker nonce key; the
                # generation tag drops straggler acks that race in late).
                self.manager.reset_pending_acks(
                    generation=len(self.world_size_history))
                t_form = time.monotonic()
                error = None
                resize_to: Optional[int] = None
                group: Optional[WorkerGroupState] = None
                try:
                    group = self._start_group(world)
                    self._note_mesh_formed(world)
                except Exception as e:  # noqa: BLE001 — restartable
                    # Formation failure (capacity vanished between the
                    # sizing decision and the gang forming — e.g. a node
                    # died mid-ping): a failure like any other, not a
                    # crash of fit().  The failure budget + backoff below
                    # decide whether to try again.
                    error = e
                if group is not None:
                    error, resize_to = self._run_incarnation(group, world)
                self.goodput.enter("idle")
                if group is not None:
                    self._teardown_group(group)
                    self._gc_drain_key()
                if resize_to is not None:
                    carry_target = resize_to
                    continue  # not a failure: re-run at the new size
                if error is None:
                    break
                failures += 1
                fc = self.run_config.failure_config
                now = time.monotonic()
                incarnation_lifetime = now - t_form
                # Crash-loop circuit breaker: the same signature dying
                # immediately, N times in a row, is deterministic — more
                # restarts only burn quota.  Fail fast with a diagnosis
                # bundle naming the signature.
                sig = _error_signature(error)
                if sig == self._last_error_sig and \
                        incarnation_lifetime < fc.crash_loop_window_s:
                    self._crash_streak += 1
                else:
                    self._crash_streak = 1
                self._last_error_sig = sig
                if fc.crash_loop_threshold and \
                        self._crash_streak >= fc.crash_loop_threshold:
                    error = self._trip_crash_loop(sig, error)
                    break
                # Failure budget: rolling window when configured (a long
                # run shouldn't die on its Nth *unrelated* failure),
                # lifetime counter otherwise.
                if fc.failure_window_s is not None:
                    self._failure_times.append(now)
                    cutoff = now - fc.failure_window_s
                    while self._failure_times and \
                            self._failure_times[0] < cutoff:
                        self._failure_times.popleft()
                    over_budget = len(self._failure_times) > fc.max_failures
                else:
                    over_budget = failures > fc.max_failures
                if over_budget:
                    break
                from ..util import telemetry
                telemetry.inc("ray_tpu_train_worker_restarts_total", world)
                # Bounded exponential backoff between re-formations: a
                # flapping cluster (or a slow-to-release resource pool)
                # shouldn't be hammered with group formation attempts.
                # An incarnation that proved stable resets the ladder.
                if fc.restart_backoff_initial_s > 0:
                    if incarnation_lifetime >= fc.restart_backoff_reset_s:
                        self._backoff_s = fc.restart_backoff_initial_s
                    delay = min(self._backoff_s, fc.restart_backoff_max_s)
                    self._backoff_s = min(
                        self._backoff_s * fc.restart_backoff_factor,
                        fc.restart_backoff_max_s)
                    telemetry.observe(
                        "ray_tpu_train_restart_backoff_seconds", delay)
                    self.goodput.enter("restart")
                    time.sleep(delay)
                # Restart: fresh group resumes from the latest committed
                # checkpoint (reference: controller failure policy ->
                # group teardown -> re-create -> resume, SURVEY §3.4 step 6).
                # Prefer the previous size so the policy grace-waits for the
                # dead group's resources to release instead of greedily
                # under-sizing on the first partial fit.
                carry_target = world

        finally:
            # Any escape from the fit loop (group-formation
            # failure, KeyboardInterrupt) must still stop the
            # monitor thread and join pending bundle writers.
            self.watchdog.stop()
            self.goodput.finish()
            if getattr(self.run_config.checkpoint_config,
                       "emergency_replica", False):
                self._release_orphan_pins()
        rank0 = sorted((r for r in self._reports if r["rank"] == 0),
                       key=lambda r: r["time"])
        last_metrics = rank0[-1]["metrics"] if rank0 else {}
        latest = self.manager.latest()
        total_phase_s = sum(self._phase_totals.values())
        step_phases = {
            "seconds": {k: round(v, 6)
                        for k, v in sorted(self._phase_totals.items())},
            "fraction": {k: round(v / total_phase_s, 4)
                         for k, v in sorted(self._phase_totals.items())}
            if total_phase_s > 0 else {},
        } if self._phase_totals else None
        return Result(
            metrics=last_metrics,
            checkpoint=Checkpoint(latest) if latest else None,
            error=error,
            all_reports=self._reports,
            num_failures=failures,
            num_drains=self.num_drains,
            world_size_history=self.world_size_history,
            goodput=self.goodput.summary(),
            step_phases=step_phases,
            mesh=dict(self._mesh_axes) if self._mesh_axes else None)
